"""Batched, mesh-sharded WGL search over many independent histories.

This is the TPU-native replacement for the reference's per-key CPU fan-out
(`jepsen.independent/checker` bounded-pmaps subhistory checks,
`jepsen/src/jepsen/independent.clj:266-317`): every key's history is
encoded into one shared shape bucket, the lockstep-frontier kernel from
`jepsen_tpu.ops.wgl` is vmapped over the leading key axis, and all arrays
are placed with a `NamedSharding` over a 1-D device mesh ("keys"), so XLA
partitions the search across devices with no collectives — per-key checks
are embarrassingly parallel, and ICI stays idle by design.

Keys whose history can't be encoded (or that resolve trivially) are
handled on the host; keys the device search leaves "unknown" fall back to
the Python oracle, mirroring `knossos.competition/analysis` racing engines.
"""

from __future__ import annotations

import functools
import time as _time
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from .. import devices as _devices
from .. import fleet as _fleet
from .. import metrics as _metrics
from .. import occupancy as _occ
from .. import watchdog as _watchdog
from ..analysis import lockwatch
from ..history import History
from ..models.core import Model
from ..ops import adapt as _adapt
from ..ops import wgl_ref
from ..ops.encode import INF, Encoded, EncodingUnsupported, _pad_to, encode


def shared_shape_bucket(encs: Sequence[Encoded]) -> Optional[dict]:
    """One (n_pad, ic, S, O, w_eff) shape bucket covering every key
    of a streamed fan-out — `wgl.check(shape_bucket=...)` pads each
    encoding into it, so the whole key set compiles ONE kernel per
    ladder bucket instead of one per raw shape.

    Root cause of the r05 `independent_100x2k` regression (+8 s over
    r04 on the same code): the 100 keys' raw encodings straddle
    several (n_pad, W_eff) buckets — n_pad buckets at 64-op
    granularity, W_eff at 8 — so a handful of keys each paid a fresh
    XLA compile + python-dispatch warm-up INSIDE the measured window
    (shard walls: p50 0.23 s vs max 1.3 s on this machine — the
    stragglers in `fleet.summarize()` are exactly the first key of
    each bucket), and whether those compiles hit the persistent
    compile cache varies round to round. One shared bucket makes the
    cost one compile, paid once, cache-state-independent.

    Only meaningful when every key takes the same kernel branch —
    callers split keys at window_raw 32 (narrow/wide) and bucket
    each group separately. Returns None for empty input."""
    if not encs:
        return None
    from ..ops.wgl import _packable
    # max-based so a MIXED batch (preflight's vmap batch-kernel plan)
    # gets the branch encode_batch would take; uniform groups —
    # the streamed callers — are unaffected
    wide = max(e.window_raw for e in encs) > 32
    w_eff = 0
    ic_eff = 8
    for e in encs:
        if wide:
            w_eff = max(w_eff, _pad_to(e.window_raw, 32))
        else:
            w_eff = max(w_eff, max(8, _pad_to(e.window_raw, 8)))
        ic_eff = max(ic_eff, _pad_to(max(e.n_info, 1), 8))
    return {
        "n_pad": max(len(e.inv) for e in encs),
        "ic_pad": max(len(e.inv_info) for e in encs),
        "S": max(e.table.shape[0] for e in encs),
        "O": max(e.table.shape[1] for e in encs),
        "w_eff": w_eff,
        "ic_eff": min(ic_eff, max(len(e.inv_info) for e in encs)),
        "n_cap": max(e.n_ok for e in encs),
        # bucket-wide packed-table bit: one unpackable key must not
        # split the bucket into two kernel variants (the whole point
        # is ONE executable per ladder bucket)
        "pack": all(_packable(e) for e in encs),
    }


def default_mesh(axis: str = "keys", n_devices: Optional[int] = None):
    """A 1-D mesh over every visible device — or the first
    `n_devices` of them: a lane group never needs more shards than
    lanes, and surplus shards are not free (their inert lanes still
    compute every lockstep round), so width-bounded callers like the
    service pass their batch ceiling here."""
    import jax
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices())
    if n_devices:
        devs = devs[:int(n_devices)]
    return Mesh(devs, (axis,))


@dataclass
class BatchEncoded:
    """A batch of per-key encodings padded into one shape bucket."""

    n_keys: int            # real keys (batch may be padded beyond this)
    n_pad: int
    ic_pad: int
    window: int
    table_s: int
    table_o: int
    inv: np.ndarray        # (Bk, n_pad) i32
    ret: np.ndarray        # (Bk, n_pad) i32
    opcode: np.ndarray     # (Bk, n_pad) i32
    sufminret: np.ndarray  # (Bk, n_pad+1) i32
    inv_info: np.ndarray   # (Bk, ic_pad) i32
    opcode_info: np.ndarray  # (Bk, ic_pad) i32
    table: np.ndarray      # (Bk, S, O) i32
    n_ok: np.ndarray       # (Bk,) i32
    n_info: np.ndarray     # (Bk,) i32


def encode_batch(encs: Sequence[Encoded], batch_pad: int = 1) -> BatchEncoded:
    """Pad per-key encodings into a common bucket and stack them.

    `batch_pad`: round the key axis up to a multiple (usually the mesh
    size) with dummy keys; dummy lanes have n_ok = 0 and an empty frontier
    after round one, so they cost nothing and their verdicts are ignored.
    """
    nk = len(encs)
    bk = _pad_to(nk, batch_pad)
    n_pad = max(len(e.inv) for e in encs)
    ic_pad = max(len(e.inv_info) for e in encs)
    W = max(e.window for e in encs)
    S = max(e.table.shape[0] for e in encs)
    O = max(e.table.shape[1] for e in encs)

    inv = np.full((bk, n_pad), INF, dtype=np.int32)
    ret = np.full((bk, n_pad), INF, dtype=np.int32)
    opc = np.zeros((bk, n_pad), dtype=np.int32)
    suf = np.full((bk, n_pad + 1), INF, dtype=np.int32)
    iinv = np.full((bk, ic_pad), INF, dtype=np.int32)
    iopc = np.zeros((bk, ic_pad), dtype=np.int32)
    table = np.full((bk, S, O), -1, dtype=np.int32)
    n_ok = np.zeros(bk, dtype=np.int32)
    n_info = np.zeros(bk, dtype=np.int32)
    for i, e in enumerate(encs):
        inv[i, :len(e.inv)] = e.inv
        ret[i, :len(e.ret)] = e.ret
        opc[i, :len(e.opcode)] = e.opcode
        suf[i, :len(e.sufminret)] = e.sufminret
        iinv[i, :len(e.inv_info)] = e.inv_info
        iopc[i, :len(e.opcode_info)] = e.opcode_info
        s, o = e.table.shape
        table[i, :s, :o] = e.table
        n_ok[i] = e.n_ok
        n_info[i] = e.n_info
    return BatchEncoded(n_keys=nk, n_pad=n_pad, ic_pad=ic_pad, window=W,
                        table_s=S, table_o=O, inv=inv, ret=ret, opcode=opc,
                        sufminret=suf, inv_info=iinv, opcode_info=iopc,
                        table=table, n_ok=n_ok, n_info=n_info)


def _batch_capacities(bk: int, W: int, n_pad: int, L: int = 0):
    """Frontier K / memo H / backlog B *per key*, mirroring the single-
    history tuning in wgl._pick_capacities. Two measured facts drive
    this (see wgl.check's fast-path note): (1) narrow frontiers explore
    far fewer redundant configs — K=256 beats K=2048 by an order of
    magnitude on valid histories; (2) the memo table must stay well
    under ~60% load or probe dedup degrades into re-exploration (the
    old per-lane H=2^16 thrashed at ~185k explored configs per lane and
    blew the search up ~18x). Whole-batch caps: the narrow path's
    (Bk, K, W, 2W) bool intermediate stays under 128M elements; the
    packed path's (Bk, K, W, L) uint32 successor tensor (its memory
    driver — see wgl.check's byte-budget policy) under 128 MB; memo
    tables (16 B/slot) under ~2 GB across the batch."""
    import os

    if L:  # packed multi-lane kernel (W > 32): byte budget over the
        #    (Bk, K, W, L) u32 successor tensor, as in wgl.check.
        #    Floor at the kernel minimum (16), NOT wgl.check's 64 —
        #    that floor is safe only at bk=1; here it could blow the
        #    whole-batch byte budget several-fold on many wide keys.
        budget_bytes = 128 * 1024 * 1024
        K = max(16, min(1024, budget_bytes // max(1, bk * W * L * 4 * 3)))
        cap = int(os.environ.get("JEPSEN_TPU_MAX_FRONTIER", "0"))
        if cap:
            K = max(16, min(K, cap))
    else:
        budget = 128 * 1024 * 1024  # bool elements across the batch
        cap = max(16, budget // max(1, bk * 2 * W * W))
        # 64 for the fast path: narrow beams do ~K/depth of the work on
        # valid lanes (see wgl.check), but vmap lanes can't escalate, so
        # keep some breadth for the occasional exhaustive key.
        K = min(64, cap)
    K = 1 << (K.bit_length() - 1)
    H = 1 << 21 if n_pad > 2048 else 1 << 19
    cap = max(1 << 16, 2**31 // (16 * max(1, bk)))
    # both kernels mask probe indices with `& (H - 1)` — H MUST stay a
    # power of two or most slots become unreachable
    H = min(H, 1 << (cap.bit_length() - 1))
    # packed rows are (L + Il + 2) u32s — a 2^16 backlog at L=3 is
    # ~1.5 MB/key, and wide wavefronts (C(W/2, W) live configs) spill
    # hard; the bool path keeps the smaller backlog
    B = 1 << 16 if L else 1 << 14
    return K, H, B


@functools.lru_cache(maxsize=16)
def _raw_batched(n_pad: int, ic_pad: int, W: int, S: int, O: int,
                 K: int, H: int, B: int, chunk: int, probes: int,
                 L: int = 0, accel: bool = False,
                 batched: bool = False):
    """The UNJITTED (init_fn, chunk_fn) pair for one shape bucket —
    shared by the vmap path below and the mesh scheduler's shard_map
    wrapper (parallel/mesh.py), so both transforms trace the exact
    same kernel closure. With `batched` (narrow kernel only), the
    returned chunk_fn natively carries the lane axis inside its round
    loop — `wgl32.chunk_fn_batched` — instead of needing an outer
    vmap."""
    if W <= 32:
        from ..ops.wgl32 import _build_search32
        return _build_search32(n_pad, ic_pad, S, O, K, H, B, chunk,
                               probes, W=W, accel=accel,
                               batched=batched)
    from ..ops.wgln import _build_searchN
    return _build_searchN(n_pad, ic_pad, S, O, K, H, B, chunk,
                          probes, W=W, L=L, accel=accel)


@functools.lru_cache(maxsize=16)
def _compiled_batched(n_pad: int, ic_pad: int, W: int, S: int, O: int,
                      K: int, H: int, B: int, chunk: int, probes: int,
                      L: int = 0, accel: bool = False):
    """vmap the shape-bucket kernel over the key axis and jit it.
    Windows that fit a uint32 lane use the bitmask fast path (W here is
    already the trimmed W_eff, padded to a multiple of 8); wider
    windows use the packed multi-lane kernel (ops/wgln.py, W padded to
    a multiple of 32, L = W//32 lanes) — the same ~11x-at-W=71 win the
    single-history path gets, now on the mesh-sharded batch."""
    import jax

    init_fn, chunk_fn = _raw_batched(n_pad, ic_pad, W, S, O, K, H, B,
                                     chunk, probes, L=L, accel=accel)
    vinit = jax.vmap(init_fn)
    vchunk = jax.jit(jax.vmap(chunk_fn), donate_argnums=(1,))
    return vinit, vchunk


def _backend_ready_or_fallback(time_limit: Optional[float]) -> bool:
    """Bounded wait for jax backend init (util.backend_ready): the
    first device call on a wedged accelerator runtime hangs the
    calling thread forever, and these entry points run on the MAIN
    thread. The wait is capped at HALF the caller's budget so the
    host-oracle fallback keeps a real share. False -> the caller must
    take the host path."""
    from ..util import backend_ready
    return backend_ready(min(60.0, time_limit / 2) if time_limit
                         else None)


def _all_host(model: Model, histories: Sequence[History],
              deadline: Optional[float], oracle_fallback: bool,
              key_indices: Optional[Sequence[int]] = None) -> list[dict]:
    """Device plane unavailable (init timeout): decide every key with
    the host oracle inside the remaining budget, or report why not.
    `key_indices` maps positions to the caller's batch indices so the
    recorded shard telemetry names the right key."""
    out = []
    for i, h in enumerate(histories):
        t0 = _time.monotonic()
        base = {"valid?": "unknown", "cause": "backend-init-timeout",
                "op_count": len(h)}
        res = (_oracle_fallback(model, h, deadline, base)
               if oracle_fallback else base)
        # engine "oracle-fallback" only when the oracle actually ran
        # (_oracle_fallback skips past-deadline and sets no engine)
        _annotate_shard(res,
                        key_index=(key_indices[i] if key_indices
                                   is not None else i),
                        device="host",
                        engine=str(res.get("engine") or "none"),
                        t0=t0, wall_s=_time.monotonic() - t0)
        out.append(res)
    return out


def _oracle_fallback(model: Model, history: History,
                     deadline: Optional[float], device_res: dict) -> dict:
    """Re-check a device-"unknown" history with the host oracle inside
    whatever time remains, annotating why the device declined
    (competition semantics). ALWAYS annotates `device_cause` — even on
    the deadline-expired path that returns the device result untouched
    otherwise — so a fallback verdict can never lose the reason the
    device declined."""
    remaining = (deadline - _time.monotonic()
                 if deadline is not None else None)
    cause = device_res.get("cause") or "undecided"
    if remaining is not None and remaining <= 0:
        out = dict(device_res)
        out.setdefault("device_cause", cause)
        out.setdefault("fallback", "skipped: deadline expired")
        return out
    ref = wgl_ref.check(model, history, time_limit=remaining)
    ref["device_cause"] = ref.get("device_cause", cause)
    ref.setdefault("engine", "oracle-fallback")
    return ref


def _annotate_shard(res: dict, *, key_index: int, device: str,
                    engine: str, t0: float, wall_s: float,
                    device_index: Optional[int] = None,
                    fault: Optional[dict] = None,
                    extra: Optional[dict] = None) -> dict:
    """Stamp a per-key `shard` telemetry block onto a result and
    record it into the ambient metrics registry + RunStatus
    (fleet.record_shard). Returns the result for chaining."""
    shard = {"key_index": key_index, "device": device,
             "engine": engine, "t0": round(t0, 4),
             "wall_s": round(wall_s, 4),
             "valid?": res.get("valid?"),
             "op_count": res.get("op_count")}
    if device_index is not None:
        shard["device_index"] = device_index
    if res.get("cause") is not None:
        shard["cause"] = res.get("cause")
    if res.get("device_cause") is not None:
        shard["device_cause"] = res.get("device_cause")
    if fault is not None:
        shard["fault"] = fault
    if extra:
        shard.update(extra)
    res["shard"] = shard
    _fleet.record_shard(shard)
    return res


def check_streamed(model: Model, histories: Sequence[History],
                   time_limit: Optional[float] = None,
                   max_configs: int = 50_000_000,
                   oracle_fallback: bool = True,
                   encs: Optional[Sequence[Encoded]] = None,
                   race: Optional[bool] = None,
                   register_keys: bool = True,
                   key_indices: Optional[Sequence[int]] = None
                   ) -> list[dict]:
    """Per-key single-kernel checks fanned out over the visible devices
    by a thread pool (one worker per device, `jax.default_device`
    pinning). This is the fast path for *large* per-key histories: the
    per-round cost of the search kernel scales with frontier rows, and a
    vmapped batch pays every lane's rows every round until the slowest
    lane finishes — measured on 16 x 2k-op cas-register keys, streaming
    singles beats the lockstep vmap batch by ~10x. The vmap path
    (strategy="vmap") remains the right call for many tiny histories,
    where per-call dispatch dominates and lanes finish together."""
    import jax

    from ..ops import wgl

    deadline = _time.monotonic() + time_limit if time_limit else None
    if not _backend_ready_or_fallback(time_limit):
        return _all_host(model, histories, deadline, oracle_fallback,
                         key_indices=key_indices)
    if race and not oracle_fallback:
        raise ValueError(
            "race=True requires oracle_fallback (racing IS the oracle "
            "running concurrently); pass race=False to see raw device "
            "verdicts")
    status = _fleet.get_default()
    # register_keys=False: check_batched already registered the whole
    # key set (host-decided keys included) with the run status.
    # Registered BEFORE the admission gate: begin_keys resets the
    # decided counter, and rejected keys close via key_done below.
    if status.enabled and register_keys and len(histories) > 1:
        status.begin_keys(len(histories))
    # Admission preflight (analysis/preflight): each kernel branch's
    # shared shape bucket sizes every lane of its group by the group
    # maxima, so one key whose plan blows the device budget makes the
    # shared kernel infeasible for its WHOLE group — those keys are
    # rejected statically, before any compile or device byte, exactly
    # like the history_lint gate; keys in an admissible group proceed.
    # Device path only: a host fallback has no HBM budget, so nothing
    # is planned (or recorded) for it.
    from ..analysis import preflight
    rejected = preflight.gate_fanout(model, histories, encs=encs,
                                     where="parallel.streamed") or {}

    def _rejected_result(i: int) -> dict:
        # annotated like any other shard so fleet key accounting
        # (keys.decided, /status.json) still closes the key
        ki = key_indices[i] if key_indices is not None else i
        return _annotate_shard(
            dict(rejected[i], op_count=len(histories[i])),
            key_index=ki, device="none", engine="preflight",
            t0=_time.monotonic(), wall_s=0.0)

    # With oracle_fallback the rejection is not terminal: the device
    # attempt is skipped statically, but the host oracle (no HBM
    # budget) still decides the key inside the deadline — the same
    # competition semantics as a device "unknown" (see one() below).
    # Without it, the structured rejection IS the verdict.
    if not oracle_fallback:
        if len(rejected) == len(histories):
            return [_rejected_result(i) for i in range(len(histories))]
    devices = jax.devices()
    results: list[Optional[dict]] = [None] * len(histories)
    if not oracle_fallback:
        for i in rejected:
            results[i] = _rejected_result(i)
    if race is None:
        # On a real accelerator the host CPU is otherwise idle, so
        # racing the per-key device search against the host oracle
        # takes whichever engine wins each key for free; on a CPU
        # backend both engines would contend for the same cores, so
        # the direct device path (with oracle fallback) stays faster.
        race = oracle_fallback and \
            jax.default_backend() not in ("cpu",)

    # One shared shape bucket per kernel branch: every key compiles
    # the same executable (see shared_shape_bucket — the
    # independent_100x2k straggler fix)
    bucket_n = bucket_w = None
    if encs is not None and len(histories) > 1:
        # rejected keys must not size the shared bucket: the whole
        # point of the per-group rejection is that the admitted
        # group's kernel is NOT padded to the infeasible key's shape
        admitted = [e for j, e in enumerate(encs) if j not in rejected]
        bucket_n = shared_shape_bucket(
            [e for e in admitted if e.window_raw <= 32])
        bucket_w = shared_shape_bucket(
            [e for e in admitted if e.window_raw > 32])

    def one(dev, i_hist):
        label = _fleet.device_label(dev)
        di = devices.index(dev) if dev in devices else None
        # the index the TELEMETRY names: the caller's batch index when
        # this is a sub-batch of a bigger key set (check_batched)
        ki = (key_indices[i_hist] if key_indices is not None
              else i_hist)
        t0 = _time.monotonic()
        retries = 0
        rej = rejected.get(i_hist)
        if rej is not None:
            # preflight-rejected: the device attempt is skipped
            # statically, but the host oracle (no HBM budget) still
            # decides the key — competition semantics, same as a
            # device "unknown" (oracle_fallback is True here; the
            # False case pre-filled the structured rejection above)
            status.device_state(label, "fallback", key_index=ki)
            res = _oracle_fallback(
                model, histories[i_hist], deadline,
                dict(rej, op_count=len(histories[i_hist])))
            if "preflight" in rej:   # keep the plan that scratched
                res.setdefault("preflight", rej["preflight"])
            return _annotate_shard(
                res, key_index=ki, device=label, device_index=di,
                engine=str(res.get("engine") or "preflight"),
                t0=t0, wall_s=_time.monotonic() - t0)
        status.device_state(label, "searching", key_index=ki)
        remaining = None
        if deadline is not None:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                res = {"valid?": "unknown", "cause": "timeout",
                       "op_count": len(histories[i_hist])}
                return _annotate_shard(
                    res, key_index=ki, device=label,
                    device_index=di, engine="none", t0=t0,
                    wall_s=0.0)
        try:
            with jax.default_device(dev):
                if race:
                    from ..checker import _race_competition
                    res = _race_competition(
                        model, histories[i_hist], remaining,
                        device=dev, max_configs=max_configs,
                        enc=encs[i_hist] if encs else None)
                    engine = str(res.get("engine") or "device")
                else:
                    enc_i = encs[i_hist] if encs else None
                    sb = None
                    if enc_i is not None:
                        sb = (bucket_n if enc_i.window_raw <= 32
                              else bucket_w)
                    res = wgl.check(model, histories[i_hist],
                                    time_limit=remaining,
                                    max_configs=max_configs,
                                    enc=enc_i, shape_bucket=sb)
                    engine = "device"
                    if res.get("valid?") == "unknown" and oracle_fallback:
                        status.device_state(label, "fallback",
                                            key_index=ki)
                        retries = 1
                        res = _oracle_fallback(model, histories[i_hist],
                                               deadline, res)
                        # a past-deadline skip sets no engine: the
                        # shard stays "device" (the oracle never ran)
                        engine = str(res.get("engine") or engine)
                return _annotate_shard(
                    res, key_index=ki, device=label,
                    device_index=di, engine=engine, t0=t0,
                    wall_s=_time.monotonic() - t0,
                    extra={"retries": retries})
        except Exception as e:  # noqa: BLE001 — a device fault on one
            # key must not void the whole batch (and must not leave a
            # None hole when raised inside a worker thread): capture
            # the traceback as a structured fleet event, keep going,
            # and still let the host oracle decide the key
            fault = _fleet.fault_event(e, device=label,
                                       key_index=ki)
            status.fault(fault)
            status.device_state(label, "fault", key_index=ki)
            res = {"valid?": "unknown",
                   "cause": f"error: {type(e).__name__}: {e}"[:300],
                   "op_count": len(histories[i_hist])}
            engine = "fault"
            if oracle_fallback:
                res = _oracle_fallback(model, histories[i_hist],
                                       deadline, res)
                engine = str(res.get("engine") or engine)
            res["fault"] = fault
            return _annotate_shard(
                res, key_index=ki, device=label, device_index=di,
                engine=engine, t0=t0,
                wall_s=_time.monotonic() - t0, fault=fault)

    wd = _watchdog.get_default()
    if len(devices) == 1 or len(histories) == 1:
        for i in range(len(histories)):
            if results[i] is not None:  # preflight-rejected key
                continue
            if wd.cancelled():
                # run-wide soft-cancel (an escalated stall): the
                # remaining keys report partial progress, not silence
                _fill_stalled(results, histories, key_indices, wd)
                break
            results[i] = one(devices[0], i)
        return results  # type: ignore[return-value]

    # One worker thread per device, each draining its OWN pending
    # queue (keys assigned LPT by encoded op count) and stealing the
    # smallest pending key off the heaviest queue when it runs dry —
    # so uneven keys never serialize behind a statically pinned
    # device. Between keys the finishing worker additionally ACTS on
    # the fleet's rebucket signal: when the completed shard walls show
    # work_skew past fleet.REBUCKET_SKEW_X, pending keys move
    # smallest-first off the busiest device's queue onto the laziest's
    # (fleet.steal_plan — the hint PR 12's summarize() only computed),
    # recorded as a `fleet_sched` event so doctor D005 sees the skew
    # HANDLED on the rerun, not just measured.
    import threading
    from collections import deque
    est = [float(encs[i].n_ok) if encs else float(len(histories[i]))
           for i in range(len(histories))]
    labels = [_fleet.device_label(d) for d in devices]
    queues = [deque() for _ in devices]
    dev_wall = [0.0] * len(devices)
    load = [0.0] * len(devices)
    for i in sorted(range(len(histories)), key=lambda i: -est[i]):
        d = load.index(min(load))
        queues[d].append(i)
        load[d] += est[i]
    qlock = lockwatch.lock("batched.queue")

    def _claim(di):
        with qlock:
            if queues[di]:
                return queues[di].popleft()
            donor = max(range(len(devices)),
                        key=lambda d: sum(est[j] for j in queues[d]))
            if donor == di or not queues[donor]:
                return None
            # smallest-first off the heaviest queue: moving a
            # straggler key would just relocate the imbalance
            j = min(queues[donor], key=lambda j: est[j])
            queues[donor].remove(j)
            return j

    def _rebalance():
        if len(devices) < 2:
            return
        with qlock:
            walls = {labels[d]: dev_wall[d]
                     for d in range(len(devices))}
            pending = {labels[d]: [(est[j], j) for j in queues[d]]
                       for d in range(len(devices))}
        plan = _fleet.steal_plan(pending, walls)
        if plan is None:
            return
        with qlock:
            fdi = labels.index(plan["from"])
            tdi = labels.index(plan["to"])
            # keys may have been claimed since the snapshot — move
            # only what is still pending
            moved = [j for j in plan["keys"] if j in queues[fdi]]
            for j in moved:
                queues[fdi].remove(j)
                queues[tdi].append(j)
        if not moved:
            return
        _fleet.record_sched_event("fleet_sched", {
            "event": "rebucket", "from": plan["from"],
            "to": plan["to"],
            "keys": [key_indices[j] if key_indices is not None else j
                     for j in moved],
            "skew_before": plan["skew_before"],
            "est_moved": plan["est_moved"]})

    def worker(dev):
        di = devices.index(dev)
        while True:
            if wd.cancelled():
                return
            i = _claim(di)
            if i is None:
                return
            if results[i] is not None:  # preflight-rejected key
                continue
            results[i] = one(dev, i)
            with qlock:
                dev_wall[di] += float(
                    (results[i].get("shard") or {}).get("wall_s")
                    or 0.0)
            _rebalance()

    # daemon only under cancel-escalation: that is the one mode where
    # the join below may abandon a hung worker, and a non-daemon zombie
    # would then block interpreter exit forever
    abandonable = wd.enabled and wd.escalation == "cancel"
    threads = [threading.Thread(target=worker, args=(d,),
                                daemon=abandonable)
               for d in devices]
    for t in threads:
        t.start()
    if not abandonable:
        for t in threads:
            t.join()
        return results  # type: ignore[return-value]
    # Bounded wait: a worker hung inside a device round never returns
    # — per-chunk deadline checks cannot reach it (they run BETWEEN
    # chunks). Once the watchdog escalates, healthy workers wind down
    # at their next poll; give them a short grace, then abandon the
    # hung remainder and report stalled partials for their keys.
    grace_until = None
    while True:
        alive = [t for t in threads if t.is_alive()]
        if not alive:
            break
        alive[0].join(min(0.25, wd.poll_s))
        if wd.cancelled():
            now = _time.monotonic()
            if grace_until is None:
                grace_until = now + min(5.0, wd.stall_s)
            elif now > grace_until:
                break
    _fill_stalled(results, histories, key_indices, wd)
    return results  # type: ignore[return-value]


def _fill_stalled(results: list, histories, key_indices, wd) -> None:
    """Stalled partial verdicts for keys the abandoned/cancelled
    fan-out never decided: {"valid?": "unknown", "cause": "stalled"}
    plus the fleet-level progress counters (keys decided so far)."""
    decided = sum(1 for r in results if r is not None)
    ev = (wd.stalls or [{}])[-1]
    stall = {k: ev.get(k) for k in ("source", "age_s", "beats",
                                    "escalation") if ev.get(k)
             is not None}
    for i, r in enumerate(results):
        if r is not None:
            continue
        ki = key_indices[i] if key_indices is not None else i
        t0 = _time.monotonic()
        res = {"valid?": "unknown", "cause": "stalled",
               "op_count": len(histories[i]),
               "partial": {"keys_decided": decided,
                           "keys_total": len(results)},
               "stall": dict(stall)}
        results[i] = _annotate_shard(
            res, key_index=ki, device="fleet", engine="stalled",
            t0=t0, wall_s=0.0)


def check_batched(model: Model, histories: Sequence[History],
                  time_limit: Optional[float] = None,
                  max_configs: int = 50_000_000,
                  mesh=None, oracle_fallback: bool = True,
                  chunk: int = 1024, strategy: str = "auto") -> list[dict]:
    """Check many independent histories against `model` on the
    accelerator. Returns one result dict per history, in order.

    strategy: "mesh" — the lane-packed mesh scheduler
    (parallel/mesh.py: per-device lane groups, retire/refill,
    telemetry-driven rebucketing + work stealing; the default
    multi-device path on "auto", degrading to the decisions below
    when the mesh plan is infeasible or fewer than 2 devices exist);
    "vmap" — one mesh-sharded lockstep search over the whole key
    batch (all lanes step until the slowest finishes; best when
    histories are small and uniform, and the path the multi-chip
    dryrun's narrow/wide/mesh2d sections validate — an explicitly
    passed `mesh` with strategy="auto" pins it); "stream" — per-key
    single-kernel checks fanned over devices (best for large
    histories; see check_streamed); "auto" — mesh for >=4 encodable
    keys, else stream when the biggest history exceeds ~512 completed
    ops.

    `max_configs` is a per-key exploration budget. With `oracle_fallback`,
    keys the device leaves "unknown" are re-checked by the host oracle
    (competition semantics); pass False to see raw device verdicts.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    # Device stats are int32; cap the budget so the explored counter can
    # reach it without wrapping (it grows by at most K per round).
    max_configs = min(max_configs, 2**30)
    results: list[Optional[dict]] = [None] * len(histories)
    status = _fleet.get_default()
    if status.enabled and len(histories) > 1:
        status.begin_keys(len(histories))
    encs: list[Encoded] = []
    lanes: list[int] = []  # lane -> history index
    for i, h in enumerate(histories):
        t_enc = _time.monotonic()
        try:
            e = encode(model, h)
        except EncodingUnsupported as exc:
            if oracle_fallback:
                res = wgl_ref.check(model, h, time_limit=time_limit)
                res.setdefault("device_cause", f"encoding: {exc}")
            else:
                res = {"valid?": "unknown", "cause": f"encoding: {exc}",
                       "op_count": len(h)}
            results[i] = _annotate_shard(
                res, key_index=i, device="host", engine="host",
                t0=t_enc, wall_s=_time.monotonic() - t_enc)
            continue
        if e.n_ok == 0:
            results[i] = _annotate_shard(
                {"valid?": True, "op_count": e.n_info}, key_index=i,
                device="host", engine="host", t0=t_enc,
                wall_s=_time.monotonic() - t_enc)
            continue
        encs.append(e)
        lanes.append(i)

    if not encs:
        return results  # type: ignore[return-value]

    if strategy == "auto":
        # The DEFAULT multi-device path is the mesh scheduler
        # (parallel/mesh.py): lane-packed lockstep rounds with
        # retire/refill, telemetry-driven rebucketing, and work
        # stealing — it subsumes both older trades (streaming's
        # per-key dispatch cost AND the vmap batch paying every lane
        # until the slowest finishes). An explicitly passed mesh
        # still pins the vmap path (the MULTICHIP dryrun sections
        # and their tests prove that path as-is); small key sets
        # fall through to the old stream/vmap decision below.
        from . import mesh as _mesh_mod
        if mesh is None and _mesh_mod.enabled() \
                and len(encs) >= _mesh_mod.MIN_MESH_KEYS:
            strategy = "mesh"
    if strategy == "mesh":
        from . import mesh as _mesh_mod
        out = _mesh_mod.check_mesh(
            model, [histories[i] for i in lanes], encs=encs,
            time_limit=time_limit, max_configs=max_configs,
            mesh=mesh, oracle_fallback=oracle_fallback,
            key_indices=lanes, chunk=chunk)
        if out is not None:
            for i, res in zip(lanes, out):
                results[i] = res
            return results  # type: ignore[return-value]
        # degraded (single device / backend timeout / infeasible
        # mesh plan): fall through to the old auto decision
        strategy = "auto"
    if strategy == "auto":
        # An explicitly passed mesh pins the caller to the mesh-sharded
        # vmap path. On a CPU backend, large per-key histories stream
        # (see check_streamed's rationale: lockstep lanes pay every
        # key's rows until the slowest finishes, and host cores run
        # the single-key kernel fast). On an ACCELERATOR the trade
        # flips — the per-round cost is serialized-latency-bound, so
        # lockstep vmap amortizes the same ~hundreds-of-us round over
        # EVERY key at once, while streaming pays it per key,
        # sequentially, on however few devices exist (round-4 measured
        # 197.7 s streamed vs 12.2 s on a lone CPU for 100 x 2k keys).
        # Wide-window keys no longer force streaming: the vmap batch
        # builds the packed multi-lane kernel (wgln.py) for W > 32.
        from ..util import safe_backend
        on_accel = safe_backend() not in (None, "cpu")
        stream_wins = (not on_accel
                       and max(e.n_ok for e in encs) > 512) \
            or (on_accel and len(encs) < 4)
        strategy = "stream" if (mesh is None and stream_wins) \
            else "vmap"
    if strategy == "stream":
        streamed = check_streamed(
            model, [histories[i] for i in lanes],
            time_limit=time_limit, max_configs=max_configs,
            oracle_fallback=oracle_fallback,
            encs=encs, register_keys=False, key_indices=lanes)
        for i, res in zip(lanes, streamed):
            results[i] = res
        return results  # type: ignore[return-value]
    if strategy != "vmap":
        raise ValueError(f"unknown strategy {strategy!r}")

    deadline0 = _time.monotonic() + time_limit if time_limit else None
    if not _backend_ready_or_fallback(time_limit):
        host = _all_host(model, [histories[i] for i in lanes],
                         deadline0, oracle_fallback, key_indices=lanes)
        for i, res in zip(lanes, host):
            results[i] = res
        return results  # type: ignore[return-value]

    if mesh is None:
        mesh = default_mesh()
    # Multi-axis meshes (e.g. ("hosts", "chips") on a multi-host pod)
    # shard the key axis over the PRODUCT of all axes: per-key search
    # needs no collectives, so DCN between hosts stays as idle as ICI.
    axis = tuple(mesh.axis_names) if len(mesh.axis_names) > 1 \
        else mesh.axis_names[0]
    nd = mesh.devices.size

    # Admission preflight for the lockstep vmap batch (the streamed
    # branch gates inside check_streamed): encode_batch pads EVERY
    # lane to the batch maxima and the one kernel keeps ceil(lanes/nd)
    # lanes' buffers resident per device, so the admitted plan is THAT
    # batch kernel (mode="batch"), not the per-key kernels. An
    # infeasible batch is not necessarily dead — per-key kernels are
    # the memory-minimal execution — so degrade to the streamed path,
    # whose own per-group gate rejects what even a lone kernel cannot
    # fit; either way nothing compiles or touches the device first.
    from ..analysis import preflight
    bad_pf = preflight.gate_fanout(model, histories, encs=encs,
                                   where="parallel.batched",
                                   mode="batch", n_devices=nd,
                                   on_infeasible="degrade")
    if bad_pf:
        streamed = check_streamed(
            model, [histories[i] for i in lanes],
            time_limit=time_limit, max_configs=max_configs,
            oracle_fallback=oracle_fallback,
            encs=encs, register_keys=False, key_indices=lanes)
        for i, res in zip(lanes, streamed):
            results[i] = res
        return results  # type: ignore[return-value]

    batch = encode_batch(encs, batch_pad=nd)
    bk = batch.inv.shape[0]
    # Fast-path trimming, mirroring wgl.check: successor-row count
    # R = K*(W_eff + ic_eff) drives probe traffic, so materialize only
    # what the widest history in the batch needs.
    w_raw = max(e.window_raw for e in encs)
    inv_info, opcode_info = batch.inv_info, batch.opcode_info
    ic_pad = batch.ic_pad
    ic_eff = max(8, _pad_to(int(batch.n_info.max()), 8))
    if ic_eff < ic_pad:
        inv_info = inv_info[:, :ic_eff]
        opcode_info = opcode_info[:, :ic_eff]
        ic_pad = ic_eff
    if w_raw <= 32:
        W = max(8, _pad_to(w_raw, 8))
        L = 0
    else:
        # packed multi-lane kernel: window as L uint32 lanes; rounds
        # are light (bit math, probe-only dedup), so poll often
        W = _pad_to(w_raw, 32)
        L = W // 32
        chunk = min(chunk, 128)
    probes = 4
    K, H, B = _batch_capacities(bk, W, batch.n_pad, L)
    from ..util import safe_backend
    accel = safe_backend() not in (None, "cpu")
    vinit, vchunk = _compiled_batched(
        n_pad=batch.n_pad, ic_pad=ic_pad, W=W,
        S=batch.table_s, O=batch.table_o, K=K, H=H, B=B,
        chunk=chunk, probes=probes, L=L, accel=accel)

    def shard(x):
        spec = PartitionSpec(axis) if x.ndim else PartitionSpec()
        return jax.device_put(x, NamedSharding(mesh, spec))

    import jax.numpy as jnp
    consts = tuple(shard(jnp.asarray(a)) for a in (
        batch.inv, batch.ret, batch.opcode, batch.sufminret,
        inv_info, opcode_info, batch.table,
        batch.n_ok, batch.n_info,
        np.full(bk, max_configs, dtype=np.int32)))
    carry = jax.tree.map(shard, vinit(jnp.zeros(bk, dtype=jnp.int32)))

    deadline = _time.monotonic() + time_limit if time_limit else None
    t0 = _time.monotonic()
    timed_out = False
    stalled = False
    mx = _metrics.get_default()
    # keys already decided on the host (trivial/unsupported encodings)
    # before the vmap loop — the live decided count builds on them
    decided_base = (status.snapshot()["keys"]["decided"]
                    if status.enabled else 0)
    wd = _watchdog.get_default()
    # the watchdog heartbeat for the whole lockstep batch: one beat
    # per poll; a vchunk call that hangs on a wedged mesh stops
    # beating and the monitor declares the batch stalled. First-beat
    # grace covers the vmapped kernel's compile (folded into the
    # first vchunk call).
    hb = wd.register("wgl-batched", device=f"mesh[{nd}]",
                     grace_s=300.0)
    s = None  # last packed poll; None if cancelled before any poll
    kern = "wgl32" if not L else "wgln"
    n_polls = 0
    # device observatory window over the whole lockstep batch: HBM
    # sampled at the existing vmap poll cadence (host allocator query,
    # no extra device round-trip); the per-lane results below carry
    # their own device's slice of the measured block
    dm = _devices.get_default()
    dmark = dm.mark(where="batched") if dm.enabled else None
    # lane -> mesh device index, known statically (NamedSharding lays
    # the key axis out in contiguous blocks of bk//nd lanes): ONE
    # derivation shared by the per-round heatmap points (the
    # per-device column strip) and the per-key result attribution
    # below — two copies would let the strip and the shard labels
    # silently disagree about which device a lane ran on
    lanes_per_dev = max(1, bk // nd)
    # per-lane occupancy bookkeeping: previous cumulative rounds per
    # lane (anchors each drain) and a bounded budget of heatmap
    # points — silent caps read as full coverage, so exhaustion is
    # recorded on the series itself
    prev_rounds = np.zeros(bk, dtype=np.int64)
    prev_expl = np.zeros(bk, dtype=np.int64)
    # per-lane adaptive hints: a lockstep vmap batch shares ONE K, so
    # the ladder cannot re-bucket a single lane — but the policy's
    # recommendation is recorded per lane per poll, naming the
    # capacity each lane actually needs (the mesh-sharding rework of
    # ROADMAP item 3 consumes these)
    hint_ladder = (_adapt.ladder_for(K, k_min=max(32, K // 16), step=8)
                   if L else _adapt.LADDER32)
    occ_budget = 8192
    try:
        while True:
            if wd.cancelled(hb):
                stalled = True
                break
            t_poll = _time.monotonic()
            carry, summary = vchunk(consts, carry)
            # one packed (Bk, SUMMARY_HEAD + ring) poll transfer:
            # [fr_cnt, flags, stats, bk, per-round occupancy ring]
            s = np.asarray(summary)
            n_polls += 1
            if dmark is not None:
                dm.sample(where="batched", mx=mx)
            fr_cnt, flags, stats = s[:, 0], s[:, 1:4], s[:, 4:10]
            found = flags[:, 0] != 0
            empty = fr_cnt == 0
            budget = stats[:, 0] >= max_configs
            live = ~(found | empty | budget)
            live[batch.n_keys:] = False
            wd.beat(hb, live_keys=int(live.sum()),
                    decided_keys=int(
                        (found | empty)[:batch.n_keys].sum()),
                    configs_explored=int(
                        stats[:batch.n_keys, 0].sum()))
            fr_real = fr_cnt[:batch.n_keys]
            fills = np.round(fr_real / max(K, 1), 4)
            if mx.enabled:
                # per-lane adaptive hints ride the lanes series only
                # — the metrics-off poll loop stays overhead-free
                # (PR-2's zero-cost contract)
                r_delta = np.maximum(stats[:, 5].astype(np.int64)
                                     - prev_rounds, 0)
                e_delta = np.maximum(stats[:, 0].astype(np.int64)
                                     - prev_expl, 0)
                occupied = np.where(r_delta > 0, e_delta
                                    / np.maximum(r_delta, 1), 0.0)
                hints = [_adapt.recommend(hint_ladder,
                                          float(occupied[lane]))
                         for lane in range(batch.n_keys)]
            prev_expl = stats[:, 0].astype(np.int64)
            prev_rounds_next = stats[:, 5].astype(np.int64)
            if mx.enabled:
                mx.series(
                    "wgl_batched_chunks",
                    "per-poll state of the mesh-sharded batched search"
                ).append({
                    "wall_s": round(_time.monotonic() - t0, 4),
                    "poll_s": round(_time.monotonic() - t_poll, 4),
                    "live_keys": int(live.sum()),
                    "decided_keys": int(
                        (found | empty)[:batch.n_keys].sum()),
                    "frontier_total": int(fr_cnt[:batch.n_keys].sum()),
                    "backlog_total": int(s[:batch.n_keys, 10].sum()),
                    "explored_total": int(
                        stats[:batch.n_keys, 0].sum())})
                # per-lane fill, one vector per poll: stragglers and
                # empty lanes visible without per-lane transfers (the
                # fill rides the same packed summary)
                mx.series(
                    "wgl_batched_lanes",
                    "per-poll per-lane frontier fill of the "
                    "mesh-batched search").append({
                        "poll": n_polls - 1,
                        "wall_s": round(_time.monotonic() - t0, 4),
                        "K": K, "kernel": kern,
                        "live": int(live.sum()),
                        "empty_lanes": int((fr_real == 0).sum()),
                        "fill": [float(f) for f in fills],
                        # the per-lane adaptive recommendation (the
                        # bucket a solo search of this lane would run)
                        "hints": [int(h) for h in hints]})
                # per-lane per-ROUND drain for the round x lane
                # heatmap, bounded; exhaustion is recorded, not silent
                rounds_series = mx.series(
                    "wgl_batched_rounds",
                    "per-round per-lane frontier fill drained from "
                    "the vmapped kernel rings (round x lane heatmap "
                    "input)")
                if occ_budget > 0:
                    for lane in range(batch.n_keys):
                        rows, _ = _occ.drain_chunk(
                            s[lane], int(prev_rounds[lane]), K)
                        for r in rows[:max(0, occ_budget)]:
                            occ_budget -= 1
                            rounds_series.append({
                                "round": r["round"], "lane": lane,
                                "fill": r["fill"],
                                "frontier": r["frontier"],
                                # mesh-device attribution: the heatmap
                                # renders a per-device column strip
                                # from this field
                                "device": min(lane // lanes_per_dev,
                                              nd - 1)})
                    if occ_budget <= 0:
                        rounds_series.append({
                            "round": -1, "lane": -1, "fill": 0.0,
                            "frontier": 0,
                            "note": "point budget exhausted; later "
                                    "rounds not drained"})
                        occ_budget = -1  # emit the marker once
            prev_rounds = prev_rounds_next
            if status.enabled:
                status.batched_poll(
                    live=int(live.sum()),
                    decided=(decided_base
                             + int((found | empty)[:batch.n_keys].sum())),
                    total=batch.n_keys,
                    frontier_total=int(fr_cnt[:batch.n_keys].sum()),
                    backlog_total=int(s[:batch.n_keys, 10].sum()),
                    explored_total=int(stats[:batch.n_keys, 0].sum()))
                status.occupancy_poll({
                    "mode": "batched", "kernel": kern,
                    "platform": f"mesh[{nd}]",
                    "K": K,
                    "fill_last": round(float(fills.mean()), 4),
                    "fill_mean": round(float(fills.mean()), 4),
                    "lanes": {
                        "n": batch.n_keys,
                        "fill_min": round(float(fills.min()), 4),
                        "fill_max": round(float(fills.max()), 4),
                        "empty": int((fr_real == 0).sum())}},
                    search_id="batched")
            if not live.any():
                break
            if deadline is not None and _time.monotonic() > deadline:
                timed_out = True
                break
    finally:
        wd.unregister(hb)
    wall = _time.monotonic() - t0
    hbm_block = (dm.measured(dmark, where="batched")
                 if dmark is not None else None)

    if s is None:
        # soft-cancelled before the first poll landed: synthesize an
        # all-undecided summary so every lane reports a stalled partial
        s = np.zeros((bk, 11), dtype=np.int32)
        fr_cnt, flags, stats = s[:, 0], s[:, 1:4], s[:, 4:10]
        found = flags[:, 0] != 0
        empty = np.zeros(bk, dtype=bool)
        budget = np.zeros(bk, dtype=bool)
    overflow = flags[:, 1]
    # lane -> device: lanes_per_dev (above) maps the contiguous
    # NamedSharding blocks back to mesh devices
    devs_flat = list(mesh.devices.flat)
    for lane, hist_i in enumerate(lanes):
        e = encs[lane]
        n_total = int(e.n_ok + e.n_info)
        hits, ins = int(stats[lane, 3]), int(stats[lane, 4])
        rounds = int(stats[lane, 5])
        # "W" matches wgl.py's convention: the lane's actual window;
        # "W_pad" is the batch-shared padded kernel width
        detail = {"W": e.window_raw, "W_pad": W, "K": K,
                  "configs_explored": int(stats[lane, 0]),
                  "batch_keys": batch.n_keys, "batch_wall_s": round(wall, 4),
                  "util": {
                      "rounds": rounds,
                      "frontier_fill": round(
                          int(stats[lane, 0]) / max(rounds * K, 1), 4),
                      "memo_hit_rate": _occ.memo_hit_rate(hits, ins)},
                  # the lane's occupancy coordinates: which heatmap
                  # row (wgl_batched_rounds series) this key is, and
                  # where its beam ended up
                  "occupancy": {
                      "lane": lane, "K": K,
                      "fill_last": round(
                          int(fr_cnt[lane]) / max(K, 1), 4),
                      "rounds": rounds,
                      # whole-run adaptive hint: the ladder bucket a
                      # solo search of this key would have settled at
                      "hint": _adapt.recommend(
                          hint_ladder,
                          int(stats[lane, 0]) / max(rounds, 1))}}
        engine = "device-vmap"
        if found[lane]:
            res = {"valid?": True, "op_count": n_total, **detail}
        elif empty[lane] and not overflow[lane]:
            res = {"valid?": False, "op_count": n_total,
                   "max_linearized": int(stats[lane, 2]), **detail}
        else:
            cause = ("stalled" if stalled
                     else "backlog-overflow" if overflow[lane]
                     else "config-limit" if budget[lane] else "timeout")
            res = {"valid?": "unknown", "cause": cause,
                   "op_count": n_total, **detail}
            if stalled:
                # the anti-"nothing to show" contract: what this lane
                # had explored when the run was declared stalled
                res["partial"] = {
                    "configs_explored": int(stats[lane, 0]),
                    "rounds": rounds,
                    "ops_linearized": int(stats[lane, 2])}
            if oracle_fallback and not timed_out and not stalled:
                res = _oracle_fallback(model, histories[hist_i],
                                       deadline, res)
                engine = str(res.get("engine") or engine)
        di = min(lane // lanes_per_dev, nd - 1)
        if hbm_block is not None:
            # per-device attribution of the measured window: each lane
            # carries ITS device's slice (the lane->device layout is
            # the contiguous-block NamedSharding above)
            dev_label = _fleet.device_label(devs_flat[di])
            dev_hbm = (hbm_block.get("devices") or {}).get(dev_label)
            res["hbm"] = {"device": dev_label,
                          "stats_available": dev_hbm is not None,
                          "peak_measured": (dev_hbm or {}).get(
                              "peak_measured")}
            if dev_hbm is None:
                res["hbm"]["stats_unavailable"] = True
        results[hist_i] = _annotate_shard(
            res, key_index=hist_i,
            device=_fleet.device_label(devs_flat[di]),
            device_index=di, engine=engine, t0=t0,
            # lockstep lanes all pay the batch wall; per-lane rounds /
            # explored are the honest imbalance signal here
            wall_s=wall,
            extra={"rounds": rounds,
                   "configs_explored": int(stats[lane, 0])})
    return results  # type: ignore[return-value]
