"""TPU data parallelism: per-key sub-histories sharded across devices.

The reference copes with expensive checks by splitting a test into
independent keys and checking each key's subhistory on a CPU thread pool
(`jepsen/src/jepsen/independent.clj:266-317`, bounded-pmap). Here the same
split becomes accelerator data parallelism: per-key histories are encoded
into a shared shape bucket, the WGL search kernel is vmapped over the key
axis, and the batch is laid out over a `jax.sharding.Mesh` so each device
searches its own keys with zero cross-device communication.

Fleet observability (doc/OBSERVABILITY.md): every per-key result
carries a `shard` telemetry block (device, engine, wall, faults) —
recorded into the ambient metrics registry and `fleet.RunStatus` —
and `independent.py` derives the `util.fleet` straggler/imbalance
aggregates from them via `fleet.summarize`.
"""

from .batched import (BatchEncoded, check_batched, check_streamed,
                      default_mesh, encode_batch)
from .mesh import check_mesh

__all__ = ["BatchEncoded", "check_batched", "check_mesh",
           "check_streamed", "default_mesh", "encode_batch"]
