"""Distributed-tracing spans for clients and nemeses.

The reference's only SUT-side tracing lives in the dgraph suite
(`dgraph/src/jepsen/dgraph/trace.clj:1-73`): OpenCensus scoped spans
around client calls, span/trace ids captured into ops, export to a
Jaeger collector. This module is the framework-level equivalent with
no external collector dependency: spans carry trace/span/parent ids
and wall-clock bounds, nest through a thread-local context, annotate
ops via `context()`, and export as OTLP-flavored JSON lines — a file
Jaeger/otel tooling can ingest, and the store can keep as a run
artifact.

    tracer = trace.Tracer(sampled=True)
    with tracer.span("invoke", attrs={"f": "read"}):
        ...
        op = {**op, "span": tracer.context()}
    tracer.export(os.path.join(run_dir, "trace.jsonl"))

A disabled tracer (sampled=False, the default construction for tests
without an endpoint — sampler semantics of trace.clj:9-14) makes every
call a no-op so instrumented clients cost nothing.
"""

from __future__ import annotations

import contextlib
import json
import os
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_s: float
    end_s: Optional[float] = None
    attrs: dict = field(default_factory=dict)
    annotations: list = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentSpanId": self.parent_id,
            "startTimeUnixNano": int(self.start_s * 1e9),
            "endTimeUnixNano": (int(self.end_s * 1e9)
                                if self.end_s else None),
            "attributes": dict(self.attrs),
            "events": list(self.annotations),
        }


class Tracer:
    """Thread-safe span collector with thread-local nesting."""

    def __init__(self, sampled: bool = True,
                 service: str = "jepsen_tpu"):
        self.sampled = sampled
        self.service = service
        self._local = threading.local()
        self._lock = threading.Lock()
        self.spans: list[Span] = []
        self._listeners: list = []

    def add_listener(self, fn) -> None:
        """Register fn(event, span), called with event "start" when a
        span opens and "end" when it closes — the hook
        `fleet.RunStatus` uses to follow checker phase spans live.
        Listener failures never break the traced code."""
        if fn not in self._listeners:
            self._listeners.append(fn)

    def _notify(self, event: str, sp: "Span") -> None:
        for fn in self._listeners:
            try:
                fn(event, sp)
            except Exception:  # noqa: BLE001
                pass

    # -- current-span plumbing ----------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    @contextlib.contextmanager
    def span(self, name: str, attrs: Optional[dict] = None,
             parent: Optional[dict] = None):
        """Scoped span (with-trace, trace.clj:40-49): nested spans in
        the same thread share the trace id and chain parent ids.
        `parent` — a {"trace-id", "span-id"} context captured via
        `context()` — adopts an EXPLICIT parent when the thread-local
        stack is empty: the competition checker's engine threads use
        it so their spans nest under the caller's check() trace
        instead of starting disconnected roots."""
        if not self.sampled:
            yield None
            return
        cur = self.current()
        if cur is not None:
            trace_id, parent_id = cur.trace_id, cur.span_id
        elif parent:
            trace_id = parent.get("trace-id") or secrets.token_hex(16)
            parent_id = parent.get("span-id")
        else:
            trace_id, parent_id = secrets.token_hex(16), None
        sp = Span(name=name,
                  trace_id=trace_id,
                  span_id=secrets.token_hex(8),
                  parent_id=parent_id,
                  start_s=time.time(),
                  attrs=dict(attrs or {}))
        self._stack().append(sp)
        self._notify("start", sp)
        try:
            yield sp
        finally:
            sp.end_s = time.time()
            self._stack().pop()
            with self._lock:
                self.spans.append(sp)
            self._notify("end", sp)

    # -- the trace.clj surface ----------------------------------------
    def context(self) -> Optional[dict]:
        """{"trace-id", "span-id"} of the current span, for stamping
        into ops (trace.clj:51-58)."""
        sp = self.current()
        if sp is None:
            return None
        return {"trace-id": sp.trace_id, "span-id": sp.span_id}

    def annotate(self, message: str) -> None:
        """Timestamped event on the current span (trace.clj:60-64)."""
        sp = self.current()
        if sp is not None:
            sp.annotations.append({"time": time.time(),
                                   "message": str(message)})

    def attribute(self, k: str, v: Any) -> None:
        """Attribute on the current span (trace.clj:66-73 — string
        values there; anything JSON-serializable here)."""
        sp = self.current()
        if sp is not None:
            sp.attrs[str(k)] = v

    def trim(self, keep: int) -> int:
        """Drop all but the newest `keep` finished spans; returns how
        many were dropped. Long-lived processes (the service worker
        pool) rotate their tracer with this so request spans don't
        grow without bound — exports after a trim carry the recent
        window only."""
        with self._lock:
            dropped = max(0, len(self.spans) - max(0, int(keep)))
            if dropped:
                del self.spans[:dropped]
        return dropped

    # -- export --------------------------------------------------------
    def export(self, path: str) -> int:
        """Write collected spans as JSON lines; returns span count."""
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with self._lock:
            spans = list(self.spans)
        with open(path, "w") as fh:
            for sp in spans:
                fh.write(json.dumps(
                    {"resource": {"service.name": self.service},
                     **sp.to_json()}) + "\n")
        return len(spans)

    def export_perfetto(self, path: str,
                        counters: Optional[dict] = None,
                        instants: Optional[list] = None) -> int:
        """Write collected spans as a Chrome/Perfetto `trace_event`
        JSON file (see `to_perfetto`); returns span count.
        `counters` — {track: [(t_epoch_s, value), ...]} — renders as
        counter tracks under the spans (the occupancy plane's
        per-round fill / frontier / backlog graphs;
        `occupancy.perfetto_counter_tracks` builds them from a
        metrics registry). `instants` — [{"t": epoch_s, "name": ...}]
        — renders as instant-event annotations in their own lane
        (the doctor's offending-round markers;
        `doctor.perfetto_instants` builds them from a report)."""
        with self._lock:
            spans = list(self.spans)
        doc = to_perfetto([sp.to_json() for sp in spans],
                          service=self.service, counters=counters,
                          instants=instants)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as fh:
            json.dump(doc, fh)
        return len(spans)


# ---------------------------------------------------------------------------
# Chrome/Perfetto trace_event export
# ---------------------------------------------------------------------------
# The OTLP-flavored JSONL above is for Jaeger-shaped tooling; Perfetto
# (ui.perfetto.dev) and chrome://tracing want the trace_event format
# instead — and they render the checker's phase spans (encode /
# compile / device-round / host-poll, per-key fan-out, engine races)
# as a zoomable flame chart with zero extra tooling. Mapping: one
# process per service, one thread LANE per trace id (each analysis /
# engine thread gets its own row), spans as "X" complete events in
# microseconds, annotations as "i" instant events.

def perfetto_events(spans: list, service: str = "jepsen_tpu",
                    pid: int = 1) -> list:
    """`trace_event` dicts from span dicts (the `Span.to_json` /
    exported-JSONL shape). Unfinished spans (no end time) are emitted
    with zero duration rather than dropped — a crashed run's last open
    span is exactly the interesting one. `pid` names the process
    track: the default single-process export owns pid 1; the fleet
    observatory's merged export gives each replica its own pid so N
    processes render as N labeled tracks (counters/instants keep
    pids 2/3)."""
    events: list = []
    lanes: dict = {}
    pid = int(pid)
    events.append({"ph": "M", "name": "process_name", "pid": pid,
                   "tid": 0, "args": {"name": str(service)}})
    for sp in spans:
        if not isinstance(sp, dict) or sp.get("startTimeUnixNano") \
                is None:
            continue
        trace_id = str(sp.get("traceId"))
        tid = lanes.get(trace_id)
        if tid is None:
            tid = lanes[trace_id] = len(lanes) + 1
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": tid,
                           "args": {"name": f"trace {trace_id[:8]}"}})
        ts = int(sp["startTimeUnixNano"]) / 1e3  # ns -> us
        end = sp.get("endTimeUnixNano")
        dur = max(0.0, (int(end) / 1e3 - ts)) if end else 0.0
        args = {k: v for k, v in (sp.get("attributes") or {}).items()}
        for k in ("spanId", "parentSpanId"):
            if sp.get(k):
                args[k] = sp[k]
        events.append({"ph": "X", "name": str(sp.get("name")),
                       "cat": "span", "ts": ts, "dur": dur,
                       "pid": pid, "tid": tid, "args": args})
        for ann in sp.get("events") or []:
            if not isinstance(ann, dict) or ann.get("time") is None:
                continue
            events.append({"ph": "i", "s": "t",
                           "name": str(ann.get("message"))[:80],
                           "cat": "annotation",
                           "ts": float(ann["time"]) * 1e6,
                           "pid": pid, "tid": tid})
    return events


def counter_events(tracks: dict, pid: int = 2) -> list:
    """`trace_event` "C" (counter) events from
    {track_name: [(t_epoch_seconds, value), ...]} — Perfetto renders
    each named track as a step graph on its own row, time-aligned
    with the span lanes. Counters live in their OWN process lane
    (pid 2, named "counters" — `perfetto_events` owns pid 1's span
    thread lanes, and sharing tids there would let a counter
    thread_name meta rename a span row), and each track gets its own
    tid + thread_name so multi-track exports — e.g. the per-device
    `hbm bytes <dev>` lanes — sort as separate labeled rows instead
    of piling onto tid 0. Samples are emitted in timestamp order per
    track (counter graphs render wrongly from out-of-order samples);
    non-numeric values are skipped (a torn series point must not
    sink the whole export)."""
    events: list = []
    for lane, (name, pts) in enumerate(sorted((tracks or {}).items()),
                                       start=1):
        samples: list = []
        for p in pts:
            try:
                samples.append((float(p[0]), float(p[1])))
            except (TypeError, ValueError, IndexError):
                continue
        if not samples:
            continue
        if not events:
            events.append({"ph": "M", "name": "process_name",
                           "pid": pid, "tid": 0,
                           "args": {"name": "counters"}})
        samples.sort()
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": lane,
                       "args": {"name": f"counter {name}"}})
        for t, v in samples:
            events.append({"ph": "C", "name": str(name),
                           "cat": "counter", "ts": t * 1e6,
                           "pid": pid, "tid": lane,
                           "args": {"value": v}})
    return events


def instant_events(instants: list, pid: int = 3,
                   default_lane: str = "doctor findings") -> list:
    """`trace_event` "i" (instant) annotations from
    [{"t": epoch_seconds, "name": str, "lane": str?}, ...] — one
    labeled marker per point, in their own process lane (pid 3,
    "annotations") so they never rename a span or counter row. Each
    distinct `lane` value gets its own named thread row inside that
    process — the doctor's offending-round markers
    (`doctor.perfetto_instants`, the default lane) and the
    autopilot's action markers (`autopilot.perfetto_instants`, lane
    "autopilot actions") render as separate labeled strips instead of
    interleaving. Malformed entries are skipped, never a sunk
    export."""
    events: list = []
    lanes: dict = {}
    for inst in instants or []:
        try:
            ts = float(inst["t"]) * 1e6
            name = str(inst.get("name"))[:80]
        except (TypeError, KeyError, ValueError):
            continue
        if not events:
            events.append({"ph": "M", "name": "process_name",
                           "pid": pid, "tid": 0,
                           "args": {"name": "annotations"}})
        lane = str(inst.get("lane") or default_lane)
        tid = lanes.get(lane)
        if tid is None:
            tid = lanes[lane] = len(lanes) + 1
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": tid,
                           "args": {"name": lane}})
        events.append({"ph": "i", "s": "g", "name": name,
                       "cat": "annotation", "ts": ts,
                       "pid": pid, "tid": tid})
    return events


def to_perfetto(spans: list, service: str = "jepsen_tpu",
                counters: Optional[dict] = None,
                instants: Optional[list] = None) -> dict:
    """The loadable document: {"traceEvents": [...]} — the JSON object
    form both Perfetto and chrome://tracing ingest directly.
    `counters` adds counter tracks (see `counter_events`); `instants`
    adds instant-event annotations (see `instant_events`)."""
    events = perfetto_events(spans, service=service)
    if counters:
        events += counter_events(counters)
    if instants:
        events += instant_events(instants)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def perfetto_from_jsonl(jsonl_path: str,
                        service: str = "jepsen_tpu") -> dict:
    """Convert an exported OTLP-flavored trace.jsonl (Tracer.export)
    into the Perfetto document — the on-the-fly converter behind
    web.py's /runs/<id>/perfetto.json. Unparseable lines are skipped
    (a live run's file may end mid-line)."""
    spans = []
    with open(jsonl_path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict):
                svc = (obj.get("resource") or {}).get("service.name")
                if svc:
                    service = svc
                spans.append(obj)
    return to_perfetto(spans, service=service)


# Shared disabled tracer: the default for instrumented hot paths
# (checker kernels, phase spans) — every span() is a two-line no-op.
NULL_TRACER = Tracer(sampled=False)


def tracing(endpoint: Optional[str] = None,
            service: str = "jepsen_tpu") -> Tracer:
    """Tracer enabled iff an export target is configured — the
    sampler-by-endpoint semantics of trace.clj:9-14,34-38. `endpoint`
    here is the artifact path (or any truthy value for in-memory)."""
    return Tracer(sampled=bool(endpoint), service=service)


from .client import Client as _Client  # noqa: E402


class TracedClient(_Client):
    """Client wrapper spanning every op (the dgraph suites wrap their
    client bodies in with-trace; this does it generically): each
    invoke gets an "invoke <f>" span, and the completed op carries
    {"span": {"trace-id", "span-id"}}."""

    def __init__(self, client, tracer: Tracer):
        self.client = client
        self.tracer = tracer

    def open(self, test, node):
        return TracedClient(self.client.open(test, node), self.tracer)

    def setup(self, test):
        with self.tracer.span("setup"):
            return self.client.setup(test)

    def invoke(self, test, op):
        with self.tracer.span(f"invoke {op.get('f')}",
                              attrs={"process": op.get("process")}):
            ctx = self.tracer.context()
            res = self.client.invoke(test, op)
            if ctx is not None and isinstance(res, dict):
                res = {**res, "span": ctx}
            return res

    def teardown(self, test):
        with self.tracer.span("teardown"):
            return self.client.teardown(test)

    def close(self, test):
        return self.client.close(test)
