"""Fleet observability: per-device shard telemetry + live run status.

PR 2 gave the WGL kernels a metrics/trace plane (metrics.py,
doc/OBSERVABILITY.md); this module extends it UP to the fleet level —
the `jepsen.independent` fan-out that shards per-key sub-histories
across devices (`parallel/batched.py`). Before it, that plane was a
black box: worker threads swallowed device faults into generic
results, nothing recorded which key ran on which device or how
imbalanced the shards were, and long searches gave no live progress.

Two surfaces, Dapper-style always-on (Sigelman et al., 2010):

  * **Shard telemetry** — every per-key check emits one `shard` block
    (device, key index, engine, wall, retries, fault) onto its result
    and into the ambient metrics registry (`fleet_shards` series,
    `fleet_keys_total` / `fleet_faults_total` / `fleet_fallbacks_total`
    counters, `fleet_shard_seconds` histogram). `summarize()` derives
    the fleet aggregates (per-device shard counts and busy fraction,
    max-vs-median straggler ratio, fault/fallback counts) that
    `independent.py` attaches to results as `util.fleet`.
  * **RunStatus** — a thread-safe live-status object updated from the
    checker phase spans, the `ops/wgl.py` poll loop, the batched
    workers, and the interpreter's nemesis ops. `python -m jepsen_tpu
    serve` exposes its snapshot at `/status.json` (plus an
    auto-refreshing `/status` HTML panel); `JEPSEN_TPU_PROGRESS=1`
    renders the same source as a one-line console progress ticker.
    `core.run` installs one per run and mirrors throttled snapshots to
    `<store_root>/current-status.json` so an out-of-process `serve`
    can watch a live run.

Zero-cost contract (matching metrics.py): the module default is a
disabled `RunStatus` whose recording methods return immediately — no
locks, no dict traffic. `core.run` / callers install a real one via
`set_default()` / `use()`; updates happen at poll boundaries
(~100 ms+) and per-key completion, never inside device rounds.

The JSONL schemas recorded here are validated by
`scripts/telemetry_lint.py` (wired as a tier-1 test) so schema drift
is caught before a BENCH round, and documented in
doc/OBSERVABILITY.md.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
import traceback
from typing import Iterator, Optional

from . import metrics as _metrics
from .analysis import lockwatch

# Structured fault events carry the worker traceback, bounded so a
# pathological recursion error can't bloat results/JSONL.
FAULT_TB_LIMIT = 4000

# Faults kept on the live status object (results/metrics keep them all).
STATUS_FAULT_CAP = 32

STATUS_FILENAME = "current-status.json"


# Nemesis op names that CLOSE a fault window, per the nemesis package
# conventions (nemesis/combined.py): the kill/pause package heals with
# f="start"/"resume", the partitioner closes with f="stop-partition"
# (any "stop*"), and "heal"/"recover" are the generic spellings.
# Everything else ("kill", "pause", "start-partition", clock faults)
# opens or renames the window.
NEMESIS_HEAL_FS = frozenset({"start", "heal", "resume", "recover"})


def nemesis_opens_window(f) -> bool:
    """Whether a nemesis op with this `f` opens (True) or closes
    (False) the live fault window shown on /status."""
    name = str(f)
    return not (name.startswith("stop") or name in NEMESIS_HEAL_FS)


def device_label(dev) -> str:
    """A stable short label for a jax device (or any stand-in)."""
    try:
        return str(dev)
    except Exception:  # noqa: BLE001 — a label must never raise
        return "device-?"


def fault_event(exc: BaseException, *, device: Optional[str] = None,
                key_index: Optional[int] = None,
                stage: str = "device-worker",
                context: Optional[dict] = None) -> dict:
    """A device fault as a structured fleet event: type, message, the
    worker traceback (bounded), and where it happened — instead of the
    old `f"error: {e}"` string that threw the stack away. `context`
    merges extra attribution keys into the event (the autopilot's
    failed actuators stamp stage="autopilot" plus the policy rule and
    action that was being applied, so the doctor can diagnose its own
    supervisor); the envelope keys always win."""
    out = dict(context or {})
    out.update({"type": type(exc).__name__,
                "error": str(exc)[:300],
                "stage": stage,
                "device": device,
                "key_index": key_index,
                "traceback": traceback.format_exc()[-FAULT_TB_LIMIT:]})
    return out


def _fault_point(event: dict) -> dict:
    """A fault event as a `fleet_faults` series point: the event's
    own "type" key moves to "fault_type" — the JSONL exporter stamps
    every series line with {"type": "sample"}, and a point key named
    "type" would clobber that envelope (the fleet_shards series
    already uses fault_type for the same reason)."""
    p = {k: v for k, v in event.items() if k != "type"}
    p["fault_type"] = str(event.get("type"))
    return p


def record_fault(event: dict, mx=None, status=None) -> None:
    """Record one structured fault event (usually `fault_event(exc)`)
    that is NOT attached to a per-key shard — checker-level engine
    failures, profiler/device-pin declines, malformed-history gates.
    Lands in the `fleet_faults` series + `fleet_faults_total` counter
    and on the live RunStatus fault list. No-op when both planes are
    disabled — swallowing an exception without calling this is what
    the PR-5 audit removed."""
    mx = mx if mx is not None else _metrics.get_default()
    st = status if status is not None else get_default()
    if mx.enabled:
        mx.counter("fleet_faults_total",
                   "device faults captured by fleet workers").inc(
            device=str(event.get("device") or "host"))
        mx.series("fleet_faults",
                  "structured device fault events").append(
            _fault_point(event))
    if st.enabled:
        st.fault(event)


def record_shard(shard: dict, mx=None, status=None) -> None:
    """Record one per-key shard block into the ambient metrics
    registry (`fleet_shards` series + counters/histogram) and the
    ambient RunStatus. No-op when both are disabled."""
    mx = mx if mx is not None else _metrics.get_default()
    st = status if status is not None else get_default()
    if mx.enabled:
        fault = shard.get("fault")
        point = {k: v for k, v in shard.items() if k != "fault"}
        if fault:
            point["fault_type"] = fault.get("type")
        mx.series("fleet_shards",
                  "per-key shard telemetry of the independent "
                  "fan-out (device, engine, wall, faults)"
                  ).append(point)
        lbl = {"device": shard.get("device", "host"),
               "engine": shard.get("engine", "unknown")}
        mx.counter("fleet_keys_total",
                   "per-key checks completed by the fleet").inc(**lbl)
        mx.histogram("fleet_shard_seconds",
                     "wall seconds per per-key shard check").observe(
            float(shard.get("wall_s") or 0.0), **lbl)
        if fault:
            mx.counter("fleet_faults_total",
                       "device faults captured by fleet workers").inc(
                device=lbl["device"])
            mx.series("fleet_faults",
                      "structured device fault events").append(
                _fault_point(fault))
        if shard.get("engine") == "oracle-fallback":
            mx.counter("fleet_fallbacks_total",
                       "keys re-decided by the host oracle after a "
                       "device decline").inc(device=lbl["device"])
    if st.enabled:
        st.key_done(shard)


# Work-skew past this ratio (busiest vs laziest device wall) makes
# summarize() emit a rebucket_hint — below it, moving keys would churn
# the shape buckets for noise-level gains.
REBUCKET_SKEW_X = 1.2


def rebucket_hint(shards: list) -> Optional[dict]:
    """The precise scheduling signal ROADMAP item 2's mesh fan-out
    consumes: which keys to move from the busiest device to the
    laziest one to flatten the work skew. Greedy smallest-keys-first
    from the busiest device until the two walls would cross; None
    when the fleet is <2 devices or already balanced. NB the gate is
    busiest-vs-LAZIEST wall (the pair a move actually rebalances) at
    REBUCKET_SKEW_X — intentionally sharper than summarize()'s
    `work_skew` (busiest vs MEAN), so a hint can appear while
    work_skew still reads under 1.2. Pure host arithmetic over the
    shard blocks the fan-out already stamps."""
    by_dev: dict = {}
    for s in shards:
        if not isinstance(s, dict):
            continue
        dev = str(s.get("device", "host"))
        by_dev.setdefault(dev, []).append(
            (float(s.get("wall_s") or 0.0), s.get("key_index")))
    if len(by_dev) < 2:
        return None
    walls = {d: sum(w for w, _ in ks) for d, ks in by_dev.items()}
    busiest = max(walls, key=lambda d: walls[d])
    laziest = min(walls, key=lambda d: walls[d])
    w_hi, w_lo = walls[busiest], walls[laziest]
    if w_lo <= 0 and w_hi <= 0:
        return None
    skew_before = round(w_hi / max(w_lo, 1e-9), 3)
    if w_hi <= REBUCKET_SKEW_X * max(w_lo, 1e-9):
        return None
    gap = (w_hi - w_lo) / 2
    moved_keys: list = []
    moved_wall = 0.0
    # smallest keys first: moving a straggler key would just relocate
    # the imbalance; small keys pack the gap tightly. Sort by wall
    # ONLY — ties would otherwise compare key_index, which may be
    # None (summarize tolerates missing fields; so must this)
    for w, ki in sorted(by_dev[busiest], key=lambda t: t[0]):
        if moved_wall + w > gap or ki is None:
            continue
        moved_keys.append(ki)
        moved_wall += w
    if not moved_keys or moved_wall <= 0:
        # nothing movable, or only zero-wall keys fit the gap — a
        # hint that rebalances nothing is noise, not a signal
        return None
    hi_after = w_hi - moved_wall
    lo_after = w_lo + moved_wall
    return {"from": busiest, "to": laziest,
            "keys": moved_keys,
            "wall_s_moved": round(moved_wall, 4),
            "skew_before": skew_before,
            "skew_after_est": round(
                max(hi_after, lo_after) / max(min(hi_after, lo_after),
                                              1e-9), 3)}


def steal_plan(pending: dict, walls: dict,
               skew_x: float = REBUCKET_SKEW_X) -> Optional[dict]:
    """The EXECUTABLE half of `rebucket_hint`: given per-shard PENDING
    work (`{shard: [(est, key), ...]}` — est in whatever work currency
    the caller has, e.g. encoded op counts) and per-shard completed
    walls, decide which not-yet-started keys to move off the busiest
    shard onto the laziest. `rebucket_hint` names completed keys (a
    post-hoc diagnosis); this names movable ones (the live scheduler's
    input — the mesh fan-out and the streamed pool both call it
    between polls).

    Gate: busiest-vs-laziest completed wall past `skew_x`, the same
    trigger `rebucket_hint` uses. Moves the SMALLEST pending keys
    first (moving a straggler key just relocates the imbalance) until
    half the pending-work gap is packed. None when the fleet is <2
    shards, balanced, or the busiest shard has nothing left to give.
    Pure host arithmetic — unit-testable with fabricated queues."""
    if len(walls) < 2:
        return None
    busiest = max(walls, key=lambda d: walls[d])
    laziest = min(walls, key=lambda d: walls[d])
    if busiest == laziest:
        return None
    w_hi, w_lo = float(walls[busiest]), float(walls[laziest])
    if w_lo <= 0:
        # a shard with no completed wall yet is unknown, not lazy —
        # it may be grinding its first (heavy) key, and "rebalancing"
        # onto it would pile work on the actual straggler. Wait for a
        # completion on every shard before trusting the ratio (the
        # mesh scheduler's idle-pull trigger covers genuinely idle
        # shards without wall evidence).
        return None
    if w_hi <= skew_x * w_lo:
        return None
    donor = list(pending.get(busiest) or [])
    if not donor:
        return None
    have = sum(float(e) for e, _ in donor)
    lazy_have = sum(float(e) for e, _ in (pending.get(laziest) or []))
    gap = (have - lazy_have) / 2
    if gap <= 0:
        return None
    moved: list = []
    acc = 0.0
    for est, key in sorted(donor, key=lambda t: float(t[0])):
        if acc >= gap:
            break
        if moved and acc + float(est) > gap:
            # ascending order: every later key overshoots harder —
            # moving past the gap would just relocate the imbalance.
            # (The FIRST key always moves, so a queue of only-big
            # keys still sheds one.)
            break
        moved.append(key)
        acc += float(est)
    if not moved:
        return None
    return {"from": busiest, "to": laziest, "keys": moved,
            "est_moved": round(acc, 4),
            "skew_before": round(w_hi / max(w_lo, 1e-9), 3)}


def record_sched_event(series: str, point: dict, mx=None) -> None:
    """One scheduler action (`mesh_sched` / `fleet_sched` series +
    `<series>_total{event}` counter) into the ambient registry —
    schemas in doc/OBSERVABILITY.md "Mesh scheduling", linted by
    scripts/telemetry_lint.py. No-op when metrics are disabled (the
    zero-cost contract)."""
    mx = mx if mx is not None else _metrics.get_default()
    if not mx.enabled:
        return
    desc = ("scheduler events of the mesh-sharded fan-out"
            if series == "mesh_sched" else
            "rebucket actions applied by the streamed fan-out pool")
    mx.series(series, desc).append(dict(point))
    mx.counter(f"{series}_total",
               f"{series} scheduler actions").inc(
        event=str(point.get("event", "unknown")))


# Bound on rebucket-hint key lists riding compact surfaces (ledger
# records, doctor findings, /status blocks) — the full hint stays on
# the in-memory summary.
HINT_MAX_KEYS = 16


def compact_hint(hint, max_keys: int = HINT_MAX_KEYS):
    """A rebucket hint bounded for compact surfaces: long `keys`
    lists truncate-and-count (`keys_omitted`) instead of ballooning
    a record — the ONE truncation rule ledger.summarize_result and
    doctor.compact_finding share."""
    if not isinstance(hint, dict):
        return None
    out = dict(hint)
    keys = out.get("keys")
    if isinstance(keys, list) and len(keys) > max_keys:
        out["keys"] = keys[:max_keys]
        out["keys_omitted"] = len(keys) - max_keys
    return out


def summarize(shards: list) -> dict:
    """Fleet aggregates over per-key shard blocks: per-device shard
    counts / wall / busy fraction, straggler ratio (max vs median
    shard wall), the work-skew index (busiest vs mean device wall),
    engine mix, fault and fallback counts, and — when the skew says
    keys are worth moving — a `rebucket_hint` block naming which
    keys to move where (the mesh fan-out's scheduling input).
    Tolerates None entries (skipped keys) and missing fields."""
    shards = [s for s in shards if isinstance(s, dict)]
    if not shards:
        return {"keys": 0, "devices": {}, "engines": {},
                "faults": 0, "fallbacks": 0}
    per_dev: dict = {}
    engines: dict = {}
    faults = 0
    fallbacks = 0
    for s in shards:
        dev = str(s.get("device", "host"))
        d = per_dev.setdefault(dev, {"keys": 0, "wall_s": 0.0,
                                     "faults": 0, "fallbacks": 0})
        d["keys"] += 1
        d["wall_s"] += float(s.get("wall_s") or 0.0)
        eng = str(s.get("engine", "unknown"))
        engines[eng] = engines.get(eng, 0) + 1
        if s.get("fault"):
            d["faults"] += 1
            faults += 1
        if eng == "oracle-fallback":
            d["fallbacks"] += 1
            fallbacks += 1
    walls = sorted(float(s.get("wall_s") or 0.0) for s in shards)
    w_median = walls[len(walls) // 2]
    w_max = walls[-1]
    # busy fraction: each device's summed shard wall over the fleet
    # span (first shard start -> last shard end); needs t0 stamps
    t0s = [s["t0"] for s in shards if s.get("t0") is not None]
    span = None
    if t0s:
        ends = [s["t0"] + float(s.get("wall_s") or 0.0)
                for s in shards if s.get("t0") is not None]
        span = max(ends) - min(t0s)
        for d in per_dev.values():
            d["busy_frac"] = (round(min(1.0, d["wall_s"] / span), 4)
                              if span > 0 else 1.0)
    for d in per_dev.values():
        d["wall_s"] = round(d["wall_s"], 4)
    keys_per_dev = [d["keys"] for d in per_dev.values()]
    # work-skew index: busiest device's summed wall over the mean —
    # 1.0 is perfectly balanced; a lockstep mesh pays the busiest
    # device's wall, so (work_skew - 1) is the reclaimable fraction
    dev_walls = [d["wall_s"] for d in per_dev.values()]
    mean_wall = sum(dev_walls) / len(dev_walls)
    work_skew = round(max(dev_walls) / max(mean_wall, 1e-9), 3)
    return {
        "keys": len(shards),
        "device_count": len(per_dev),
        "devices": per_dev,
        "engines": engines,
        "faults": faults,
        "fallbacks": fallbacks,
        "wall_s": {"max": round(w_max, 4),
                   "median": round(w_median, 4),
                   "total": round(sum(walls), 4)},
        # lockstep/batched fleets pay max while a balanced one pays
        # ~median — this ratio IS the straggler cost
        "straggler_ratio": round(w_max / max(w_median, 1e-9), 3),
        "work_skew": work_skew,
        "imbalance": {"max_keys": max(keys_per_dev),
                      "min_keys": min(keys_per_dev),
                      "mean_keys": round(len(shards) / len(per_dev), 2)},
        "rebucket_hint": rebucket_hint(shards),
        "span_s": round(span, 4) if span is not None else None,
    }


class RunStatus:
    """Thread-safe live status of a run: phase, per-device state, key
    frontier/backlog, search progress, nemesis window, ETA.

    Writers call the small record methods (each takes the lock once);
    readers call `snapshot()` for a JSON-safe copy with derived
    fields (elapsed, ETA, rates). All record methods return
    immediately on a disabled instance."""

    def __init__(self, enabled: bool = True, test: Optional[str] = None,
                 status_file: Optional[str] = None,
                 progress: Optional[bool] = None):
        self.enabled = enabled
        self.status_file = status_file
        self.progress = (progress if progress is not None else
                         os.environ.get("JEPSEN_TPU_PROGRESS", "")
                         not in ("", "0"))
        self._lock = lockwatch.lock("fleet.status")
        self._t0 = time.monotonic()
        self._last_write = 0.0
        self._last_tick = 0.0
        self._d: dict = {
            "schema": 1,
            "active": bool(enabled),
            "test": test,
            "phase": None,
            "started": time.time(),
            "updated": time.time(),
            "keys": {"total": 0, "decided": 0, "live": 0,
                     "failures": 0},
            "devices": {},
            "search": {},
            "nemesis": {"active": False, "f": None, "since_s": None},
            "ops": {"invoked": 0, "completed": 0},
            "faults": [],
            "watchdog": {"stalls": 0, "last_source": None},
            "occupancy": {"active": False, "mode": None,
                          "kernel": None, "platform": None, "K": None,
                          "fill_last": None, "fill_mean": None,
                          "rounds_seen": 0, "rounds_dropped": 0,
                          "lanes": None, "recent": []},
        }

    # -- writers ------------------------------------------------------
    def _touch_locked(self) -> None:
        self._d["updated"] = time.time()

    def _after(self) -> None:
        """Post-update side channels (outside the lock): throttled
        status-file mirror + console progress line."""
        now = time.monotonic()
        if self.status_file and now - self._last_write > 1.0:
            self._last_write = now
            self._write_file()
        if self.progress and now - self._last_tick > 0.5:
            self._last_tick = now
            self._print_progress()

    def phase(self, name: Optional[str]) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._d["phase"] = name
            self._touch_locked()
        self._after()

    def on_span(self, event: str, span) -> None:
        """trace.Tracer listener: phase follows the innermost checker
        phase span (encode / compile / device-round / oracle-race /
        enrich ...)."""
        if not self.enabled:
            return
        if event == "start":
            self.phase(span.name)
        elif event == "end" and span.parent_id is None:
            self.phase(None)

    def begin_keys(self, total: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            k = self._d["keys"]
            k["total"] = int(total)
            k["decided"] = 0
            k["live"] = 0
            k["failures"] = 0
            self._d["keys_started"] = time.time()
            self._keys_t0 = time.monotonic()
            self._touch_locked()
        self._after()

    def device_state(self, device: str, state: str,
                     key_index: Optional[int] = None) -> None:
        if not self.enabled:
            return
        with self._lock:
            d = self._d["devices"].setdefault(
                str(device), {"state": "idle", "keys_done": 0,
                              "last_key": None, "busy_s": 0.0,
                              "faults": 0})
            d["state"] = state
            if key_index is not None:
                d["last_key"] = key_index
            self._touch_locked()
        self._after()

    def key_done(self, shard: dict) -> None:
        """One per-key shard finished (called via record_shard)."""
        if not self.enabled:
            return
        with self._lock:
            k = self._d["keys"]
            # cap at total: the batched vmap path reports decided
            # counts per poll AND per-key shards at assembly
            k["decided"] = (min(k["decided"] + 1, k["total"])
                            if k["total"] else k["decided"] + 1)
            if shard.get("valid?") is False:
                k["failures"] += 1
            d = self._d["devices"].setdefault(
                str(shard.get("device", "host")),
                {"state": "idle", "keys_done": 0, "last_key": None,
                 "busy_s": 0.0, "faults": 0})
            d["keys_done"] += 1
            d["last_key"] = shard.get("key_index")
            d["busy_s"] = round(d["busy_s"]
                                + float(shard.get("wall_s") or 0.0), 4)
            d["state"] = "idle"
            if shard.get("fault"):
                d["faults"] += 1
            self._touch_locked()
        self._after()

    def fault(self, event: dict) -> None:
        if not self.enabled:
            return
        with self._lock:
            faults = self._d["faults"]
            faults.append({k: event.get(k) for k in
                           ("type", "error", "stage", "device",
                            "key_index")})
            del faults[:-STATUS_FAULT_CAP]
            self._touch_locked()
        self._after()

    def stall(self, event: dict) -> None:
        """One watchdog stall detection (watchdog.py feeds this on top
        of the fault it records): the /status panel shows a stalled run
        as stalled, not merely quiet."""
        if not self.enabled:
            return
        with self._lock:
            w = self._d.setdefault("watchdog",
                                   {"stalls": 0, "last_source": None})
            w["stalls"] += 1
            w["last_source"] = event.get("source")
            w["last_age_s"] = event.get("age_s")
            self._touch_locked()
        self._after()

    def search_poll(self, point: dict, search_id=None) -> None:
        """One `wgl_chunks`-shaped poll from the single-search loop:
        frontier/backlog/explored plus the per-poll rate. `search_id`
        identifies WHICH search polled — concurrent searches (streamed
        multi-device workers, raced competition lanes) each diff their
        own cumulative `explored`, never each other's; the displayed
        `search` block is simply the last poll."""
        if not self.enabled:
            return
        with self._lock:
            prev_map = getattr(self, "_search_prev", None)
            if prev_map is None:
                prev_map = self._search_prev = {}
            prev = prev_map.get(search_id)
            p = dict(point)
            if prev is not None and prev.get("explored") is not None \
                    and p.get("explored") is not None:
                delta = p["explored"] - prev["explored"]
                dt = max(float(p.get("poll_s") or 0.0), 1e-9)
                if delta >= 0:
                    p["configs_per_s"] = int(delta / dt)
            prev_map[search_id] = {"explored": p.get("explored")}
            if len(prev_map) > 64:  # bounded: drop the oldest search
                prev_map.pop(next(iter(prev_map)))
            self._d["search"] = p
            self._touch_locked()
        self._after()

    def occupancy_poll(self, block: dict, search_id=None) -> None:
        """One kernel-occupancy update (doc/OBSERVABILITY.md
        "Occupancy & roofline"): the WGL poll loop reports last/mean
        frontier fill plus a window of recent per-round points
        (`recent_rounds`, folded into a bounded `recent` window the
        /occupancy panel renders); the batched fan-out reports a
        per-poll `lanes` summary instead. `search_id` keys the
        recent-rounds bookkeeping, same contract as `search_poll`:
        concurrent searches (streamed workers, raced lanes) each
        accumulate their OWN window — the scalar fields show the
        last poller (as the `search` block does), but its `recent`
        strip is never interleaved with another search's rounds."""
        if not self.enabled:
            return
        with self._lock:
            o = self._d["occupancy"]
            pts = block.pop("recent_rounds", None)
            buf_map = getattr(self, "_occ_recent", None)
            if buf_map is None:
                buf_map = self._occ_recent = {}
            buf = buf_map.setdefault(search_id, [])
            if pts:
                buf.extend(pts)
                del buf[:-120]
            if len(buf_map) > 64:  # bounded: drop the oldest search
                buf_map.pop(next(iter(buf_map)))
            o.update(block)
            o["active"] = True
            o["recent"] = list(buf)
            self._touch_locked()
        self._after()

    def batched_poll(self, *, live: int, decided: int, total: int,
                     frontier_total: int, backlog_total: int,
                     explored_total: int) -> None:
        """One poll of the mesh-batched lockstep search."""
        if not self.enabled:
            return
        with self._lock:
            k = self._d["keys"]
            k["total"] = max(k["total"], int(total))
            k["decided"] = min(int(decided), k["total"])
            k["live"] = int(live)
            if not hasattr(self, "_keys_t0"):
                self._keys_t0 = time.monotonic()
            self._d["search"] = {
                "mode": "batched-vmap",
                "frontier": int(frontier_total),
                "backlog": int(backlog_total),
                "explored": int(explored_total)}
            self._touch_locked()
        self._after()

    def nemesis_event(self, f, active: bool) -> None:
        if not self.enabled:
            return
        with self._lock:
            n = self._d["nemesis"]
            n["active"] = bool(active)
            n["f"] = None if f is None else str(f)
            n["since_s"] = round(time.monotonic() - self._t0, 3)
            self._touch_locked()
        self._after()

    def op_event(self, invoked: bool) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._d["ops"]["invoked" if invoked else "completed"] += 1
            self._touch_locked()
        # no _after(): op events are the hottest writer; the next
        # poll/key boundary refreshes the side channels

    def finish(self, valid=None) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._d["phase"] = "done"
            self._d["active"] = False
            if valid is not None:
                self._d["valid?"] = valid
            self._touch_locked()
        if self.status_file:
            self._write_file()
        if self.progress:
            self._print_progress(final=True)

    # -- readers ------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-safe copy plus derived fields: elapsed_s, decided-rate
        ETA (extrapolated from the per-key completion rate the
        `wgl_chunks`/`fleet_shards` stream feeds)."""
        with self._lock:
            d = json.loads(json.dumps(self._d, default=str))
            keys_t0 = getattr(self, "_keys_t0", None)
        d["elapsed_s"] = round(time.monotonic() - self._t0, 3)
        k = d["keys"]
        d["eta_s"] = None
        if keys_t0 is not None and k["total"] and k["decided"]:
            spent = max(time.monotonic() - keys_t0, 1e-9)
            rate = k["decided"] / spent
            remaining = max(k["total"] - k["decided"], 0)
            if rate > 0:
                d["eta_s"] = round(remaining / rate, 1)
        return d

    # -- side channels ------------------------------------------------
    def _write_file(self) -> None:
        """Atomic throttled mirror for out-of-process `serve`."""
        try:
            snap = self.snapshot()
            tmp = self.status_file + ".tmp"
            parent = os.path.dirname(self.status_file)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(tmp, "w") as fh:
                json.dump(snap, fh)
            os.replace(tmp, self.status_file)
        except OSError:
            pass  # a full disk must never fail the run

    def _print_progress(self, final: bool = False) -> None:
        try:
            s = self.snapshot()
            k = s["keys"]
            parts = [f"phase={s.get('phase') or '-'}"]
            if k["total"]:
                parts.append(f"keys {k['decided']}/{k['total']}")
                if k["failures"]:
                    parts.append(f"bad={k['failures']}")
            sr = s.get("search") or {}
            if sr.get("frontier") is not None:
                parts.append(f"frontier={sr['frontier']}")
            if sr.get("backlog"):
                parts.append(f"backlog={sr['backlog']}")
            if sr.get("configs_per_s"):
                parts.append(f"{sr['configs_per_s']} cfg/s")
            if s.get("eta_s") is not None:
                parts.append(f"eta={s['eta_s']}s")
            n = s.get("nemesis") or {}
            if n.get("active"):
                parts.append(f"nemesis={n.get('f')}")
            line = "[jepsen_tpu] " + " ".join(parts)
            end = "\n" if final else ""
            sys.stderr.write("\r" + line.ljust(78)[:120] + end)
            sys.stderr.flush()
        except Exception:  # noqa: BLE001 — progress never kills a run
            pass


NULL_STATUS = RunStatus(enabled=False, progress=False)


# -- ambient default ---------------------------------------------------------
# A plain module global (NOT thread-local), like metrics._default: the
# batched workers / engine threads must see the status the run installed.
_default: RunStatus = (
    RunStatus() if os.environ.get("JEPSEN_TPU_STATUS", "")
    not in ("", "0") else NULL_STATUS)


def get_default() -> RunStatus:
    """The ambient RunStatus — NULL_STATUS unless JEPSEN_TPU_STATUS=1
    was set at import or a caller installed one (core.run does, for
    every named run)."""
    return _default


def set_default(status: Optional[RunStatus]) -> RunStatus:
    global _default
    prev = _default
    _default = status if status is not None else NULL_STATUS
    return prev


@contextlib.contextmanager
def use(status: RunStatus) -> Iterator[RunStatus]:
    """Scoped ambient status (restores the previous on exit)."""
    prev = set_default(status)
    try:
        yield status
    finally:
        set_default(prev)


def read_status_file(store_root: str) -> Optional[dict]:
    """The throttled snapshot a (possibly other-process) run mirrors
    into its store root, or None."""
    path = os.path.join(store_root, STATUS_FILENAME)
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None
