"""Lock-guarded auto-reconnecting client wrappers.

Capability parity with jepsen.reconnect (`jepsen/src/jepsen/reconnect.clj:
1-146`): database client libraries tend to wedge their connections when
the network misbehaves, so we wrap an open function and give callers a
handle that can be re-opened under a lock without racing in-flight users.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional


class RWLock:
    """A simple writer-preferring read/write lock: many readers may hold
    it concurrently; a writer excludes everyone. The reference gets this
    from Java's ReentrantReadWriteLock (reconnect.clj:10: "multiple
    threads may acquire" the connection; only reopen is exclusive)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self):
        with self._cond:
            self._writers_waiting += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True

    def release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()


class Wrapper:
    """Holds a (re)openable connection. `open_fn()` builds a connection;
    `close_fn(conn)` tears one down; `name` is for logging."""

    def __init__(self, open_fn: Callable[[], Any],
                 close_fn: Optional[Callable[[Any], None]] = None,
                 name: str = "conn"):
        self.open_fn = open_fn
        self.close_fn = close_fn or (lambda c: None)
        self.name = name
        self.lock = RWLock()
        self.conn = None

    def open(self) -> "Wrapper":
        self.lock.acquire_write()
        try:
            if self.conn is None:
                self.conn = self.open_fn()
        finally:
            self.lock.release_write()
        return self

    def close(self) -> None:
        self.lock.acquire_write()
        try:
            self._close_locked()
        finally:
            self.lock.release_write()

    def _close_locked(self) -> None:
        if self.conn is not None:
            try:
                self.close_fn(self.conn)
            finally:
                self.conn = None

    def reopen(self) -> None:
        """Close and reopen the connection (reconnect.clj's reopen!) —
        exclusive: waits for in-flight users to drain."""
        self.lock.acquire_write()
        try:
            self._close_locked()
            self.conn = self.open_fn()
        finally:
            self.lock.release_write()

    def with_conn(self, f: Callable[[Any], Any]) -> Any:
        """Run f(conn) under the read lock: concurrent users proceed in
        parallel; reopens exclude them and wait for users to drain."""
        while True:
            self.lock.acquire_read()
            try:
                if self.conn is not None:
                    return f(self.conn)
            finally:
                self.lock.release_read()
            self.open()

    def with_retry(self, f: Callable[[Any], Any], retries: int = 1) -> Any:
        """Run f(conn); on failure, reopen and retry up to `retries`
        times before re-raising."""
        attempt = 0
        while True:
            try:
                return self.with_conn(f)
            except Exception:  # noqa: BLE001
                if attempt >= retries:
                    raise
                attempt += 1
                self.reopen()


def wrapper(open_fn, close_fn=None, name="conn") -> Wrapper:
    return Wrapper(open_fn, close_fn, name)
