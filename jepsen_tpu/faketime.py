"""libfaketime wrappers: run DB binaries under scaled/offset clocks
(parity with jepsen.faketime, `jepsen/src/jepsen/faketime.clj`): wraps an
executable in a faketime shell script so its process sees a clock that
starts offset and runs at a different rate."""

from __future__ import annotations

import random as _random
from typing import Optional

from . import control as c
from .control import nodeutil as cu
from .control.core import lit

RNG = _random.Random()


def install() -> None:
    """Install libfaketime from source on the bound node
    (faketime.clj:8-22). Uses the distro package when available, falling
    back to a source build."""
    with c.su():
        try:
            c.exec_("which", "faketime")
            return
        except Exception:  # noqa: BLE001
            pass
        c.exec_("mkdir", "-p", "/tmp/jepsen")
        with c.cd("/tmp/jepsen"):
            if not cu.file_exists("libfaketime"):
                c.exec_("git", "clone",
                        "https://github.com/wolfcw/libfaketime.git",
                        "libfaketime")
            with c.cd("libfaketime"):
                c.exec_("make")
                c.exec_("make", "install")


def script(cmd: str, init_offset: float, rate: float) -> str:
    """A shell script invoking cmd under faketime (faketime.clj:24-35):
    clock starts `init_offset` seconds skewed and runs at `rate`x."""
    off = int(init_offset)
    sign = "-" if off < 0 else "+"
    return ("#!/bin/bash\n"
            f'faketime -m -f "{sign}{abs(off)}s x{float(rate)}" '
            f'{c.expand_path(cmd)} "$@"\n')


def wrap(cmd: str, init_offset: float, rate: float) -> None:
    """Replace an executable with a faketime wrapper, moving the original
    to <cmd>.no-faketime. Idempotent (faketime.clj:37-48)."""
    orig = cmd + ".no-faketime"
    wrapper = script(orig, init_offset, rate)
    if not cu.file_exists(orig):
        c.exec_("mv", cmd, orig)
    cu.write_file(wrapper, cmd)
    c.exec_("chmod", "a+x", cmd)


def unwrap(cmd: str) -> None:
    """Remove the wrapper, restoring the original (faketime.clj:50-56)."""
    orig = cmd + ".no-faketime"
    if cu.file_exists(orig):
        c.exec_("mv", orig, cmd)


def rand_factor(factor: float) -> float:
    """A rate drawn around 1 with max/min ratio = factor
    (faketime.clj:57-65)."""
    hi = 2 / (1 + 1 / factor)
    lo = hi / factor
    return lo + RNG.random() * (hi - lo)
