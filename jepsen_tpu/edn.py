"""A small EDN reader — enough to replay real Jepsen artifacts.

The reference persists every run's history as EDN, one op map per
`prn` line (jepsen/src/jepsen/store.clj:338-346 write-history! via
jepsen.util/write-history!), e.g.

    {:process 0, :type :invoke, :f :read, :value nil, :index 0,
     :time 3291485317}

and its checker tests hand-write histories in the same shape
(jepsen/test/jepsen/checker_test.clj). Ingesting that format means a
reference run can be replayed through this framework's checker planes
for cross-validation — SURVEY §7 step 1's differential requirement.

Supported: nil/true/false, integers (incl. 123N bigints, radix 0x/0o),
floats (incl. 1.5M decimals), strings, characters, keywords, symbols,
lists, vectors, maps, sets, tagged literals (#inst/#uuid read as
strings; record tags like #jepsen.history.Op{...} read as their map),
#_ discard, and ; comments. Deliberately Python-native output:
keywords and symbols become plain strings (":type :invoke" ->
"type"/"invoke" — exactly the op-dict shape History.append expects),
vectors/lists become lists, sets become Python sets, map keys are
frozen to hashable forms.
"""

from __future__ import annotations

from typing import Any, Optional

_WS = set(" \t\n\r,")
_DELIM = _WS | set("()[]{}\"@;")
_CHAR_NAMES = {"newline": "\n", "space": " ", "tab": "\t",
               "return": "\r", "backspace": "\b", "formfeed": "\f"}
_STR_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", "b": "\b",
                "f": "\f", "\\": "\\", '"': '"'}


class EdnError(ValueError):
    pass


_DISCARD = object()  # sentinel: a #_ form was consumed here


class _Reader:
    def __init__(self, text: str):
        self.s = text
        self.i = 0
        self.n = len(text)

    # -- low level ----------------------------------------------------
    def _skip_ws(self):
        while self.i < self.n:
            c = self.s[self.i]
            if c in _WS:
                self.i += 1
            elif c == ";":
                while self.i < self.n and self.s[self.i] != "\n":
                    self.i += 1
            else:
                return

    def _peek(self) -> Optional[str]:
        return self.s[self.i] if self.i < self.n else None

    def at_eof(self) -> bool:
        self._skip_ws()
        return self.i >= self.n

    # -- forms --------------------------------------------------------
    def read(self) -> Any:
        """Read one VALUE (discards skipped; EOF mid-read raises)."""
        while True:
            v = self._read_form()
            if v is not _DISCARD:
                return v

    def _read_form(self) -> Any:
        self._skip_ws()
        if self.i >= self.n:
            raise EdnError("unexpected EOF")
        c = self.s[self.i]
        if c == "(":
            return self._read_seq(")")
        if c == "[":
            return self._read_seq("]")
        if c == "{":
            return self._read_map()
        if c == '"':
            return self._read_string()
        if c == "\\":
            return self._read_char()
        if c == "#":
            return self._read_dispatch()
        if c in ")]}":
            raise EdnError(f"unmatched {c!r} at {self.i}")
        return self._read_atom()

    def _read_seq(self, closer: str) -> list:
        self.i += 1  # opener
        out = []
        while True:
            self._skip_ws()
            if self._peek() is None:
                raise EdnError(f"unterminated sequence, wanted {closer!r}")
            if self._peek() == closer:
                self.i += 1
                return out
            v = self._read_form()
            if v is not _DISCARD:  # '[1 #_ 2]' == [1]
                out.append(v)

    def _read_map(self) -> dict:
        items = self._read_seq("}")
        if len(items) % 2:
            raise EdnError("map literal with odd number of forms")
        out = {}
        for k, v in zip(items[::2], items[1::2]):
            out[_freeze(k)] = v
        return out

    def _read_string(self) -> str:
        self.i += 1
        out = []
        while True:
            if self.i >= self.n:
                raise EdnError("unterminated string")
            c = self.s[self.i]
            self.i += 1
            if c == '"':
                return "".join(out)
            if c == "\\":
                e = self.s[self.i] if self.i < self.n else None
                if e is None:
                    raise EdnError("unterminated escape")
                self.i += 1
                if e == "u":
                    hexs = self.s[self.i:self.i + 4]
                    try:
                        out.append(chr(int(hexs, 16)))
                    except ValueError:
                        raise EdnError(
                            f"bad unicode escape \\u{hexs}") from None
                    self.i += 4
                elif e in _STR_ESCAPES:
                    out.append(_STR_ESCAPES[e])
                else:
                    raise EdnError(f"bad string escape \\{e}")
            else:
                out.append(c)

    def _read_char(self) -> str:
        self.i += 1
        j = self.i
        while j < self.n and self.s[j] not in _DELIM:
            j += 1
        tok = self.s[self.i:j]
        if not tok:
            raise EdnError("bare backslash")
        self.i = j
        if len(tok) == 1:
            return tok
        if tok in _CHAR_NAMES:
            return _CHAR_NAMES[tok]
        if tok.startswith("u") and len(tok) == 5:
            try:
                return chr(int(tok[1:], 16))
            except ValueError:
                raise EdnError(
                    f"bad unicode character \\{tok}") from None
        raise EdnError(f"unknown character literal \\{tok}")

    def _read_dispatch(self) -> Any:
        self.i += 1
        c = self._peek()
        if c == "{":  # set
            items = self._read_seq("}")
            return set(_freeze(x) for x in items)
        if c == "_":  # discard the NEXT form only; yield a sentinel so
            self.i += 1  # '[1 #_ 2]' and trailing '#_ x' stay valid
            self.read()
            return _DISCARD
        # tagged literal: #tag form. #inst/#uuid stay strings; record
        # tags (#some.ns.Op{...}) yield their map — exactly what
        # history replay wants from op records.
        j = self.i
        while j < self.n and self.s[j] not in _DELIM:
            j += 1
        tag = self.s[self.i:j]
        if not tag:
            raise EdnError("bare # dispatch")
        self.i = j
        return self.read()

    def _read_atom(self) -> Any:
        j = self.i
        while j < self.n and self.s[j] not in _DELIM:
            j += 1
        tok = self.s[self.i:j]
        if not tok:
            # a delimiter char no rule consumes (e.g. a stray "@"):
            # raising beats an empty-symbol that never advances the
            # cursor (observed: loads_all spun forever on "@")
            raise EdnError(
                f"unexpected character {self.s[self.i]!r} at {self.i}")
        self.i = j
        if tok == "nil":
            return None
        if tok == "true":
            return True
        if tok == "false":
            return False
        if tok.startswith(":"):
            return tok[1:]  # keyword -> plain string
        num = _try_number(tok)
        if num is not None:
            return num
        return tok  # symbol -> plain string


def _try_number(tok: str) -> Optional[Any]:
    t = tok
    neg = t.startswith("-")
    if t[:1] in "+-":
        t = t[1:]
    if not t or not (t[0].isdigit() or (t[0] == "." and
                                        t[1:2].isdigit())):
        return None
    body = tok
    try:
        if t.endswith("N"):
            return int(body[:-1])
        if t.endswith("M"):
            return float(body[:-1])
        if t[:2] in ("0x", "0X"):
            return int(body, 16)
        if t[:2] in ("0o", "0O"):
            return int(body, 8)
        if "/" in t:  # ratio
            a, b = body.split("/")
            if int(b) == 0:
                raise EdnError(f"ratio with zero denominator: {tok}")
            return int(a) / int(b)
        if any(ch in t for ch in ".eE"):
            return float(body)
        return int(body)
    except EdnError:
        raise  # EdnError IS a ValueError — don't demote it to a symbol
    except ValueError:
        return None


def _freeze(x: Any) -> Any:
    """Hashable view of a form, for map keys / set members."""
    if isinstance(x, list):
        return tuple(_freeze(v) for v in x)
    if isinstance(x, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in x.items()))
    if isinstance(x, set):
        return frozenset(x)
    return x


def loads(text: str) -> Any:
    """Parse ONE EDN form."""
    r = _Reader(text)
    v = r.read()
    if not r.at_eof():
        raise EdnError(f"trailing data at {r.i}")
    return v


def loads_all(text: str) -> list:
    """Parse every top-level form (the one-op-per-line history file)."""
    r = _Reader(text)
    out = []
    while not r.at_eof():
        v = r._read_form()
        if v is not _DISCARD:  # a trailing top-level '#_ x' is valid
            out.append(v)
    return out


def load_history(source: str):
    """Build a History from EDN text: either one vector of op maps, or
    one op map per line (store.clj's history.edn shape)."""
    from .history import History

    forms = loads_all(source)
    if len(forms) == 1 and isinstance(forms[0], list):
        forms = forms[0]
    h = History()
    for op in forms:
        if not isinstance(op, dict):
            raise EdnError(f"history form is not an op map: {op!r}")
        h.append(op)
    return h
