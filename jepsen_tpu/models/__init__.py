"""Consistency models for linearizability checking.

Reproduces the capability of knossos.model (an external dependency of the
reference, `jepsen/project.clj:7-33`; the protocol and cas-register model
are reproduced verbatim in the reference's tutorial,
`doc/tutorial/04-checker.md:38-95`): a model is an immutable value with a
single operation `step(op) -> model | Inconsistent`.

Two forms per model:
  * the object form here (pure Python, the correctness oracle and the
    public API), and
  * an integer-coded form in `jepsen_tpu.models.encode` used by the jitted
    TPU step functions.
"""

from .core import (
    Model,
    Inconsistent,
    inconsistent,
    is_inconsistent,
    Register,
    CASRegister,
    MultiRegister,
    Mutex,
    FIFOQueue,
    UnorderedQueue,
    NoOp,
    register,
    cas_register,
    multi_register,
    mutex,
    fifo_queue,
    unordered_queue,
    noop,
)

__all__ = [
    "Model",
    "Inconsistent",
    "inconsistent",
    "is_inconsistent",
    "Register",
    "CASRegister",
    "MultiRegister",
    "Mutex",
    "FIFOQueue",
    "UnorderedQueue",
    "NoOp",
    "register",
    "cas_register",
    "multi_register",
    "mutex",
    "fifo_queue",
    "unordered_queue",
    "noop",
]
