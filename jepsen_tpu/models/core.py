"""Object-form consistency models (the Python correctness oracle).

Capability parity with knossos.model: `Model.step(op) -> Model`, returning
an `Inconsistent` marker when the op is illegal in the current state. The
protocol shape is the one the reference documents at
`doc/tutorial/04-checker.md:38-95` (reproducing knossos's definition) and
re-defines locally at `jepsen/src/jepsen/tests/causal.clj:12-26`.

Models must be immutable values with structural equality and hashability:
the WGL search memoizes on (linearized-set, model) pairs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple


@dataclass(frozen=True)
class Inconsistent:
    """Marker returned by step when an operation is illegal."""

    msg: str

    def step(self, op) -> "Inconsistent":
        return self


def inconsistent(msg: str) -> Inconsistent:
    return Inconsistent(msg)


def is_inconsistent(m) -> bool:
    return isinstance(m, Inconsistent)


class Model:
    """Base class; subclasses are frozen dataclasses implementing step."""

    def step(self, op) -> "Model | Inconsistent":
        raise NotImplementedError

    def unreachable(self, op_counts: dict) -> bool:
        """True when this state cannot arise in a search that applies each
        history op at most once (`op_counts` maps op f -> multiplicity).
        Used to bound host-side state-space enumeration for the table-
        driven TPU kernel; states for which this returns True are pruned
        as illegal, which is sound because the search never requests
        them."""
        return False


@dataclass(frozen=True)
class NoOp(Model):
    """A model that accepts everything (knossos model/noop parity)."""

    def step(self, op):
        return self


@dataclass(frozen=True)
class Register(Model):
    """A read/write register. A read with value None matches any state
    (an unknown read)."""

    value: Any = None

    def step(self, op):
        f, v = op.f, op.value
        if f == "write":
            return Register(v)
        if f == "read":
            if v is None or v == self.value:
                return self
            return inconsistent(f"can't read {v!r} from register {self.value!r}")
        return inconsistent(f"unknown op f {f!r} for register")


@dataclass(frozen=True)
class CASRegister(Model):
    """A compare-and-set register: read / write / cas [old new].

    Semantics match the cas-register the reference's tutorial reproduces
    from knossos (`doc/tutorial/04-checker.md:60-80`): a cas succeeds only
    when the current value equals `old`; a read with value None matches
    anything.
    """

    value: Any = None

    def step(self, op):
        f, v = op.f, op.value
        if f == "write":
            return CASRegister(v)
        if f == "cas":
            cur, new = v
            if cur == self.value:
                return CASRegister(new)
            return inconsistent(f"can't CAS {self.value!r} from {cur!r} to {new!r}")
        if f == "read":
            if v is None or v == self.value:
                return self
            return inconsistent(f"can't read {v!r} from register {self.value!r}")
        return inconsistent(f"unknown op f {f!r} for cas-register")


@dataclass(frozen=True)
class Mutex(Model):
    """A single mutex: acquire / release."""

    locked: bool = False

    def step(self, op):
        f = op.f
        if f == "acquire":
            if self.locked:
                return inconsistent("cannot acquire a locked mutex")
            return Mutex(True)
        if f == "release":
            if not self.locked:
                return inconsistent("cannot release a free mutex")
            return Mutex(False)
        return inconsistent(f"unknown op f {f!r} for mutex")


@dataclass(frozen=True)
class FIFOQueue(Model):
    """A FIFO queue: enqueue / dequeue. Dequeue of value v is legal only
    when v is at the head. A dequeue with value None (unknown) matches any
    non-empty queue."""

    items: Tuple[Any, ...] = ()

    def step(self, op):
        f, v = op.f, op.value
        if f == "enqueue":
            return FIFOQueue(self.items + (v,))
        if f == "dequeue":
            if not self.items:
                return inconsistent("cannot dequeue from empty queue")
            head = self.items[0]
            if v is None or v == head:
                return FIFOQueue(self.items[1:])
            return inconsistent(f"queue head is {head!r}, not {v!r}")
        return inconsistent(f"unknown op f {f!r} for fifo-queue")

    def unreachable(self, op_counts):
        return len(self.items) > op_counts.get("enqueue", 0)


@dataclass(frozen=True)
class UnorderedQueue(Model):
    """A queue without ordering guarantees (knossos unordered-queue parity):
    dequeue may return any enqueued-but-not-dequeued element."""

    items: frozenset = frozenset()

    def step(self, op):
        f, v = op.f, op.value
        if f == "enqueue":
            return UnorderedQueue(self.items | {v})
        if f == "dequeue":
            if v in self.items:
                return UnorderedQueue(self.items - {v})
            return inconsistent(f"{v!r} is not in the queue")
        return inconsistent(f"unknown op f {f!r} for unordered-queue")

    def unreachable(self, op_counts):
        return len(self.items) > op_counts.get("enqueue", 0)


@dataclass(frozen=True)
class MultiRegister(Model):
    """A transactional multi-register (yugabyte's multi-key-acid
    model, multi_key_acid.clj:16-38): ops carry f="txn" with value =
    a list of [f k v] micro-ops over independent sub-registers; every
    mop applies atomically in order. Nil reads are always legal.

    State is a sorted (key, value) tuple so configurations stay
    hashable for the generic table encoder."""

    state: tuple = ()

    def _get(self, k):
        for kk, vv in self.state:
            if kk == k:
                return vv
        return None

    def _set(self, k, v) -> "MultiRegister":
        rest = tuple((kk, vv) for kk, vv in self.state if kk != k)
        return MultiRegister(tuple(sorted(rest + ((k, v),))))

    def step(self, op):
        mops = op.value
        if not isinstance(mops, (list, tuple)):
            return inconsistent(
                f"multi-register wants mop lists, got {mops!r}")
        cur = self
        for mop in mops:
            f, k, v = mop
            if f == "w":
                cur = cur._set(k, v)
            elif f == "r":
                if v is not None and v != cur._get(k):
                    return inconsistent(
                        f"can't read {v!r} from key {k!r} "
                        f"(= {cur._get(k)!r})")
            else:
                return inconsistent(
                    f"unknown mop f {f!r} for multi-register")
        return cur


# -- constructor conveniences (knossos model/register style) --
def register(value=None) -> Register:
    return Register(value)


def cas_register(value=None) -> CASRegister:
    return CASRegister(value)


def mutex() -> Mutex:
    return Mutex(False)


def fifo_queue() -> FIFOQueue:
    return FIFOQueue(())


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue(frozenset())


def multi_register(values: dict = None) -> MultiRegister:
    return MultiRegister(tuple(sorted((values or {}).items())))


def noop() -> NoOp:
    return NoOp()
