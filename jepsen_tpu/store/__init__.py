"""Results persistence (parity with jepsen.store,
`jepsen/src/jepsen/store.clj`): each run gets
`store/<name>/<start-time>/` with a binary `test.jepsen` block file
(crash-recoverable; see `.format`), plain-text `history.txt` /
`history.jsonl` / `results.json` artifacts, a `jepsen.log` capturing the
run's logging, and `latest` symlinks (store.clj:40-62, 375-419,
436-464). Saves happen in three phases: 0 (test map, before run), 1
(history, before analysis), 2 (results)."""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Optional

from .format import JepsenFile

BASE_DIR = "store"

# Test-map keys that are live objects, not data (store.clj:92-100).
NONSERIALIZABLE_KEYS = ("db", "os", "net", "client", "nemesis", "checker",
                        "generator", "remote", "sessions", "store_writer",
                        "model", "tracer")


def serializable_test(test: dict) -> dict:
    drop = set(NONSERIALIZABLE_KEYS) | set(
        test.get("nonserializable_keys", ()))
    return {k: v for k, v in test.items() if k not in drop}


def path(test: dict, *components) -> str:
    """store/<name>/<start-time>/<...> (store.clj:40-62)."""
    name = test.get("name") or "unnamed"
    t = test.get("start_time") or "unknown"
    root = test.get("store_root", BASE_DIR)
    return os.path.join(root, str(name), str(t), *map(str, components))


def path_bang(test: dict, *components) -> str:
    p = path(test, *components)
    os.makedirs(os.path.dirname(p) if components else p, exist_ok=True)
    return p


def _ops_dicts(history) -> list:
    out = []
    for op in history:
        out.append(op.to_dict() if hasattr(op, "to_dict") else op)
    return out


def update_symlinks(test: dict) -> None:
    """store/latest and store/<name>/latest (store.clj:300-330)."""
    d = path(test)
    for link in (os.path.join(os.path.dirname(os.path.dirname(d)),
                              "latest"),
                 os.path.join(os.path.dirname(d), "latest")):
        try:
            if os.path.islink(link):
                os.unlink(link)
            os.symlink(os.path.relpath(d, os.path.dirname(link)), link)
        except OSError:
            pass


class Writer:
    """Three-phase persistence for one run (store.clj:366-419)."""

    def __init__(self, test: dict):
        self.dir = path_bang(test)
        self.jepsen = JepsenFile(os.path.join(self.dir, "test.jepsen"), "w")
        self.history_chunks: list = []

    def save_0(self, test: dict) -> None:
        """Initial test map, before the run (store.clj:375-382)."""
        self.jepsen.write_initial_test(serializable_test(test))
        update_symlinks(test)

    def append_history_chunk(self, ops: list) -> None:
        """Incremental history persistence mid-run."""
        self.history_chunks.append(
            self.jepsen.append_history_chunk(_ops_dicts(ops)))
        self.jepsen.save()

    def save_1(self, test: dict) -> None:
        """Test + complete history (store.clj:384-399): commit history
        before analysis so a crashed analysis can be re-run."""
        ops = _ops_dicts(test.get("history") or [])
        t = serializable_test(test)
        if self.history_chunks:
            self.jepsen.write_history(t, chunk_ids=self.history_chunks)
        else:
            self.jepsen.write_history(t, ops=ops)
        with open(os.path.join(self.dir, "history.jsonl"), "w") as fh:
            for op in ops:
                fh.write(json.dumps(op, default=str) + "\n")
        with open(os.path.join(self.dir, "history.txt"), "w") as fh:
            for op in ops:
                fh.write("{:<12} {:<8} {:<12} {}\n".format(
                    str(op.get("process")), str(op.get("type")),
                    str(op.get("f")), str(op.get("value"))))

    def save_2(self, test: dict) -> None:
        """Results (store.clj:401-419)."""
        results = test.get("results") or {}
        self.jepsen.write_results(serializable_test(test), results)
        with open(os.path.join(self.dir, "results.json"), "w") as fh:
            json.dump(results, fh, indent=2, default=str)
        update_symlinks(test)

    def close(self):
        self.jepsen.close()


def load(name: str, start_time: str, store_root: str = BASE_DIR) -> dict:
    """Load a test lazily from disk (store.clj:121-131)."""
    jf = JepsenFile(os.path.join(store_root, name, str(start_time),
                                 "test.jepsen"), "r")
    return jf.read_test(lazy=True)


def load_latest(store_root: str = BASE_DIR) -> Optional[dict]:
    """Fully load the most recent run's test map — history and results
    included (store.clj:282 + load). Used by `analyze` CLI commands."""
    d = latest(store_root)
    if d is None:
        return None
    jf = JepsenFile(os.path.join(d, "test.jepsen"), "r")
    try:
        return jf.read_test(lazy=False)
    finally:
        jf.close()


def tests(store_root: str = BASE_DIR) -> dict:
    """{name: {start-time: path}} for every stored run (store.clj:226)."""
    out: dict = {}
    if not os.path.isdir(store_root):
        return out
    for name in sorted(os.listdir(store_root)):
        d = os.path.join(store_root, name)
        if not os.path.isdir(d) or name == "latest":
            continue
        runs = {}
        for t in sorted(os.listdir(d)):
            rd = os.path.join(d, t)
            if os.path.isdir(rd) and t != "latest" \
                    and not os.path.islink(rd):
                runs[t] = rd
        if runs:
            out[name] = runs
    return out


def latest(store_root: str = BASE_DIR) -> Optional[str]:
    """Path of the most recent run (store.clj:282)."""
    link = os.path.join(store_root, "latest")
    if os.path.islink(link):
        return os.path.realpath(link)
    newest = None
    for name, runs in tests(store_root).items():
        for t, p in runs.items():
            if newest is None or t > newest[0]:
                newest = (t, p)
    return newest[1] if newest else None


_log_handler: Optional[logging.Handler] = None


def start_logging(test: dict) -> None:
    """Tee logging into <dir>/jepsen.log (store.clj:436-458)."""
    global _log_handler
    stop_logging()
    h = logging.FileHandler(os.path.join(path_bang(test), "jepsen.log"))
    h.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s [%(name)s] %(message)s"))
    logging.getLogger().addHandler(h)
    _log_handler = h


def stop_logging() -> None:
    global _log_handler
    if _log_handler is not None:
        logging.getLogger().removeHandler(_log_handler)
        _log_handler.close()
        _log_handler = None
