"""The `.jepsen` binary block file format.

Capability parity with jepsen.store.format
(`jepsen/src/jepsen/store/format.clj:1-177` spec): an append-only,
CRC32-checksummed block file holding the test map, its history, and its
results, such that

  * the history is committed to disk *before* analysis begins, so a
    crashed analysis can be re-run from the file alone;
  * readers can load the test map and `valid?` without deserializing a
    multi-GB history (lazy block refs + partial maps);
  * writers append — save points never rewrite earlier bytes, they just
    append new blocks and a fresh index.

Layout (all integers little-endian; this is not the JVM):

    | b"JEPTPU\\x01\\n" (8) | index-offset (8) | block 1 | block 2 | ...

Each block:

    | length (8) | crc32 (4) | type (2) | payload ... |

`length` covers the whole block including the header. The CRC covers the
payload, then the header with the CRC field zeroed — so payloads can be
streamed before their checksum is known. Block types:

    1  index:   JSON {"root": block-id, "blocks": {id: offset}}
    2  data:    JSON value; {"__block_ref__": id} pointers may appear
                anywhere and are resolved lazily on read
    3  partial: JSON map + block-ref to a rest-map (for results: the
                small part carries "valid?", the rest can be huge)
    4  chunked: JSON {"chunks": [ids]} — a list concatenated from
                per-chunk data blocks (histories append chunk by chunk)

The header's index-offset points at the most recent index block; writing
a save point = append blocks + append index + patch the 8-byte pointer
(a single atomic-enough write). Recovery after a crash scans forward
from the last valid index and ignores any torn trailing block.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Optional

MAGIC = b"JEPTPU\x01\n"
HEADER_LEN = len(MAGIC) + 8

INDEX_BLOCK = 1
DATA_BLOCK = 2
PARTIAL_BLOCK = 3
CHUNKED_BLOCK = 4

_BLOCK_HEADER = struct.Struct("<QIH")  # length, crc32, type


class CorruptFile(Exception):
    pass


class BlockRef(dict):
    """{"__block_ref__": id} — a lazy pointer to another block."""

    def __init__(self, block_id: int):
        super().__init__(__block_ref__=block_id)

    @property
    def id(self) -> int:
        return self["__block_ref__"]


def is_block_ref(x) -> bool:
    return isinstance(x, dict) and "__block_ref__" in x and len(x) == 1


def _crc(header_sans_crc: bytes, payload: bytes) -> int:
    c = zlib.crc32(payload)
    return zlib.crc32(header_sans_crc, c) & 0xFFFFFFFF


class JepsenFile:
    """An open .jepsen block file. Writers append; readers resolve
    blocks lazily through the index."""

    def __init__(self, path: str, mode: str = "r"):
        self.path = path
        self.writable = mode in ("w", "a")
        if mode == "w" or (mode == "a" and not os.path.exists(path)):
            self.fh: BinaryIO = open(path, "w+b")
            self.fh.write(MAGIC)
            self.fh.write(struct.pack("<Q", 0))
            self.fh.flush()
            self.index: dict = {"root": 0, "blocks": {}}
            self.next_id = 1
        else:
            self.fh = open(path, "r+b" if mode == "a" else "rb")
            self._load()
            if mode == "a":
                # Never append past a torn/uncommitted tail: blocks
                # written there would be unreachable to the
                # scan-forward recovery path. Everything up to and
                # including the committed index block is known valid
                # (blocks are fsynced before the pointer moves), so
                # trim right after it — O(1), no full-file scan.
                end = self._committed_end if self._committed_end \
                    else HEADER_LEN
                self.fh.seek(0, os.SEEK_END)
                if self.fh.tell() > end:
                    self.fh.truncate(end)

    # -- low level -------------------------------------------------------
    def _load(self):
        self.fh.seek(0)
        if self.fh.read(len(MAGIC)) != MAGIC:
            raise CorruptFile(f"{self.path}: bad magic")
        ptr = self.fh.read(8)
        if len(ptr) < 8:
            raise CorruptFile(f"{self.path}: truncated file header")
        (index_off,) = struct.unpack("<Q", ptr)
        payload = None
        self._committed_end = 0  # offset just past the committed index
        if index_off:
            try:
                btype, payload = self._read_block_at(index_off)
                if btype != INDEX_BLOCK:
                    payload = None
            except CorruptFile:
                payload = None
            if payload is not None:
                self._committed_end = (index_off + _BLOCK_HEADER.size
                                       + len(payload))
        if payload is None:
            # Pointer missing, torn, or stale: recover by scanning
            # forward over the append-only block stream for the last
            # valid index block (the documented crash-recovery path).
            found = self._scan_last_index()
            if found is None and index_off:
                # The pointer claims a committed save point but neither
                # it nor the scan can reach one (e.g. early bit-rot
                # blocking the scan): refuse rather than proceed with —
                # or worse, truncate to — an empty index.
                raise CorruptFile(
                    f"{self.path}: committed index unreachable "
                    f"(pointer @{index_off} invalid, scan found no "
                    f"index block)")
            if found is not None:
                off, payload = found
                self._committed_end = (off + _BLOCK_HEADER.size
                                       + len(payload))
                if self.writable:
                    # repair the header pointer for future readers
                    self.fh.seek(len(MAGIC))
                    self.fh.write(struct.pack("<Q", off))
                    self.fh.flush()
                    os.fsync(self.fh.fileno())
        if payload is None:
            self.index = {"root": 0, "blocks": {}}
        else:
            self.index = json.loads(payload)
            self.index["blocks"] = {int(k): v for k, v
                                    in self.index["blocks"].items()}
        ids = self.index["blocks"].keys()
        self.next_id = max(ids, default=0) + 1

    def _iter_valid_blocks(self):
        """Yield (offset, btype, payload) for the contiguous run of
        valid blocks from the start of the file, stopping at the first
        torn/corrupt one."""
        offset = HEADER_LEN
        self.fh.seek(0, os.SEEK_END)
        end = self.fh.tell()
        while offset < end:
            try:
                btype, payload = self._read_block_at(offset)
            except CorruptFile:
                return
            yield offset, btype, payload
            offset += _BLOCK_HEADER.size + len(payload)

    def _scan_last_index(self) -> Optional[tuple]:
        """(offset, payload) of the last checksummed index block,
        ignoring any torn tail (the crash-recovery path)."""
        last = None
        for off, btype, payload in self._iter_valid_blocks():
            if btype == INDEX_BLOCK:
                last = (off, payload)
        return last

    def _read_block_at(self, offset: int) -> tuple:
        self.fh.seek(offset)
        header = self.fh.read(_BLOCK_HEADER.size)
        if len(header) < _BLOCK_HEADER.size:
            raise CorruptFile(f"{self.path}@{offset}: truncated header")
        length, crc, btype = _BLOCK_HEADER.unpack(header)
        if length < _BLOCK_HEADER.size:
            raise CorruptFile(f"{self.path}@{offset}: bad block length "
                              f"{length}")
        payload = self.fh.read(length - _BLOCK_HEADER.size)
        if len(payload) != length - _BLOCK_HEADER.size:
            raise CorruptFile(f"{self.path}@{offset}: truncated block")
        expect = _crc(_BLOCK_HEADER.pack(length, 0, btype), payload)
        if crc != expect:
            raise CorruptFile(f"{self.path}@{offset}: checksum mismatch")
        return btype, payload

    def _append_block(self, btype: int, payload: bytes) -> int:
        """Append a block; returns its offset."""
        assert self.writable
        self.fh.seek(0, os.SEEK_END)
        offset = self.fh.tell()
        length = _BLOCK_HEADER.size + len(payload)
        crc = _crc(_BLOCK_HEADER.pack(length, 0, btype), payload)
        self.fh.write(_BLOCK_HEADER.pack(length, crc, btype))
        self.fh.write(payload)
        return offset

    def _write_index(self):
        """Append a fresh index block and repoint the header at it."""
        payload = json.dumps({"root": self.index["root"],
                              "blocks": self.index["blocks"]}).encode()
        offset = self._append_block(INDEX_BLOCK, payload)
        # Make the appended blocks durable BEFORE the header points at
        # them, so a crash between the two writes leaves a pointer that
        # references only fully-written bytes.
        self.fh.flush()
        os.fsync(self.fh.fileno())
        self.fh.seek(len(MAGIC))
        self.fh.write(struct.pack("<Q", offset))
        self.fh.flush()
        os.fsync(self.fh.fileno())

    # -- block-level API -------------------------------------------------
    def write_data(self, value: Any, btype: int = DATA_BLOCK) -> int:
        """Append a data block; returns its logical id. The index is NOT
        saved until save() — call it to commit a save point."""
        bid = self.next_id
        self.next_id += 1
        offset = self._append_block(
            btype, json.dumps(value, default=str).encode())
        self.index["blocks"][bid] = offset
        return bid

    def read_block(self, bid: int) -> Any:
        offset = self.index["blocks"].get(int(bid))
        if offset is None:
            raise KeyError(f"no block {bid}")
        btype, payload = self._read_block_at(offset)
        value = json.loads(payload)
        if btype == CHUNKED_BLOCK:
            out: list = []
            for cid in value["chunks"]:
                out.extend(self.read_block(cid))
            return out
        if btype == PARTIAL_BLOCK:
            small = value["map"]
            rest = self.read_block(value["rest"]) if value.get("rest") \
                else {}
            return {**rest, **small}
        return value

    def resolve(self, value: Any) -> Any:
        """Recursively resolve block refs in a loaded value."""
        if is_block_ref(value):
            return self.resolve(self.read_block(value["__block_ref__"]))
        if isinstance(value, dict):
            return {k: self.resolve(v) for k, v in value.items()}
        if isinstance(value, list):
            return [self.resolve(v) for v in value]
        return value

    def save(self, root_id: Optional[int] = None):
        """Commit a save point (new index + header pointer)."""
        if root_id is not None:
            self.index["root"] = root_id
        self._write_index()

    # -- test-level API (the reference's write-initial-test! etc.,
    #    format.clj:1112-1150) ------------------------------------------
    def write_initial_test(self, test: dict) -> int:
        """Save point 0: the test map, without history/results."""
        t = {k: v for k, v in test.items()
             if k not in ("history", "results")}
        root = self.write_data(t)
        self.save(root)
        return root

    def append_history_chunk(self, ops: list) -> int:
        """Append one chunk of history ops; returns the chunk block id.
        Incremental: a crash loses at most the last chunk."""
        return self.write_data(ops)

    def write_history(self, test: dict, chunk_ids: Optional[list] = None,
                      ops: Optional[list] = None) -> int:
        """Save point 1: test + history (as a chunked block)."""
        if chunk_ids is None:
            chunk_ids = [self.append_history_chunk(ops or [])]
        hist_id = self.write_data({"chunks": chunk_ids},
                                  btype=CHUNKED_BLOCK)
        t = {k: v for k, v in test.items()
             if k not in ("history", "results")}
        t["history"] = BlockRef(hist_id)
        root = self.write_data(t)
        self.save(root)
        return root

    def write_results(self, test: dict, results: dict) -> int:
        """Save point 2: test + history + results (partial map: valid?
        loads without the rest)."""
        root_val = self.read_block(self.index["root"]) \
            if self.index["root"] else {}
        rest = {k: v for k, v in results.items() if k != "valid?"}
        rest_id = self.write_data(rest)
        res_id = self.write_data({"map": {"valid?": results.get("valid?")},
                                  "rest": rest_id}, btype=PARTIAL_BLOCK)
        t = {k: v for k, v in root_val.items() if k != "results"}
        t["results"] = BlockRef(res_id)
        root = self.write_data(t)
        self.save(root)
        return root

    def read_test(self, lazy: bool = True) -> dict:
        """The current test map. With lazy=True, history/results stay as
        LazyRef objects until accessed (format.clj's LazyTest, :1187)."""
        if not self.index["root"]:
            return {}
        raw = self.read_block(self.index["root"])
        if not lazy:
            return self.resolve(raw)
        return LazyTest(self, raw)

    def read_valid(self) -> Any:
        """Just results.valid? — without loading history or the full
        results (the web UI's fast path)."""
        if not self.index["root"]:
            return None
        raw = self.read_block(self.index["root"])
        ref = raw.get("results")
        if not is_block_ref(ref):
            return (raw.get("results") or {}).get("valid?")
        offset = self.index["blocks"].get(int(ref["__block_ref__"]))
        btype, payload = self._read_block_at(offset)
        value = json.loads(payload)
        if btype == PARTIAL_BLOCK:
            return value["map"].get("valid?")
        return value.get("valid?")

    def gc(self) -> None:
        """Rewrite the file keeping only blocks reachable from the
        current root (format.clj:911-1008)."""
        assert self.writable
        test = self.read_test(lazy=False)
        tmp = self.path + ".gc"
        out = JepsenFile(tmp, "w")
        if test.get("history") is not None or test.get("results"):
            hist = test.pop("history", []) or []
            results = test.pop("results", None)
            chunk = out.append_history_chunk(hist)
            out.write_history(test, chunk_ids=[chunk])
            if results:
                out.write_results(test, results)
        else:
            out.write_initial_test(test)
        out.close()
        self.fh.close()
        os.replace(tmp, self.path)
        self.fh = open(self.path, "r+b")
        self._load()

    def close(self):
        self.fh.close()


class LazyTest(dict):
    """A test map whose history/results load from the file on first
    access (format.clj LazyTest :1187-1216)."""

    def __init__(self, jf: JepsenFile, raw: dict):
        self._jf = jf
        super().__init__(raw)

    def __getitem__(self, k):
        v = super().__getitem__(k)
        if is_block_ref(v):
            v = self._jf.resolve(v)
            super().__setitem__(k, v)
        return v

    def get(self, k, default=None):
        try:
            return self[k]
        except KeyError:
            return default
