"""Device observatory: live HBM accounting + per-device mesh telemetry.

Every byte figure this tree reported before this module was a spec
constant — `ops/aot.py` models a v5e's 16 GiB / 819 GB/s, and
`analysis/preflight.py` admits plans against the same number — so the
admission verdicts, the roofline, and the multi-chip dryruns all ran
open-loop: nothing ever *measured* a device. This module closes the
loop from the runtime side:

  * **`DeviceMonitor`** samples `jax.local_devices()` /
    `Device.memory_stats()` (`bytes_in_use`, `peak_bytes_in_use`,
    `bytes_limit`) on the EXISTING poll cadences — the WGL chunk
    poll, the batched vmap poll, the Elle closure call — so no extra
    device round-trips exist: `memory_stats()` is a host-side
    allocator query. Backends without stats (the cpu tier-1 runs:
    `memory_stats()` returns None on jax's TFRT CPU devices) degrade
    to an explicit `stats_unavailable` marker, never a guess.
  * **measured-vs-predicted closure** — `mark()` / `measured()`
    bracket a search so its result carries `hbm_peak_measured`
    beside preflight's analytic `hbm.peak_bytes`; `HBM_DRIFT_X`
    (1.25x, either way) is the drift gate `bench.compute_regressions`
    flags `<name>:hbm` with, so P001's byte model is continuously
    validated instead of trusted.
  * **budget closure** — `measured_bytes_limit()` feeds
    `analysis/preflight.device_memory_budget` the chip's OWN
    `bytes_limit` when the backend reports one, so admission budgets
    stop assuming every chip is a v5e (env override still wins, the
    spec constant stays the fallback).

Telemetry lands in two linted series (scripts/telemetry_lint.py,
doc/OBSERVABILITY.md "Device & memory plane"): `hbm` (one point per
device per poll: bytes_in_use / peak_bytes_in_use / bytes_limit) and
`device_poll` (one point per poll: where it sampled, device count,
how many devices actually reported stats). `/status.json` carries an
`hbm` block and `python -m jepsen_tpu serve` renders `/devices`;
`occupancy.perfetto_counter_tracks` turns the `hbm` series into
per-device Perfetto counter lanes.

Zero-cost contract (matching metrics/fleet/ledger): the ambient
default is a disabled `NULL_MONITOR` whose `sample()` returns
immediately. `bench.py` and `core.run` install a real one;
`JEPSEN_TPU_DEVICES=1` enables it ambiently.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Iterator, Optional

# Measured-vs-predicted drift gate: a search whose measured HBM peak
# lands more than this factor away from preflight's analytic
# `hbm.peak_bytes` (either direction) is flagged `<name>:hbm` by
# bench.compute_regressions — an over-prediction wastes admission
# capacity, an under-prediction admits plans that OOM.
HBM_DRIFT_X = 1.25

# Sampling throttle: the WGL cpu poll loop runs at a few hundred Hz on
# tiny shapes; allocator queries are cheap but not free, and per-round
# resolution of a *memory* series is noise. ~20 Hz keeps every real
# poll cadence (>= 75 ms on tunneled accelerators) fully sampled.
MIN_INTERVAL_S = 0.05

_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def _backend_up() -> bool:
    """Has a jax default backend ALREADY initialized in this process?
    A pure peek — never takes the init lock, never spawns the init
    probe: the monitor must be safe to call from admission paths that
    promise zero device work (preflight's contract), and a wedged
    accelerator runtime hangs init rather than raising."""
    try:
        from jax._src import xla_bridge
        return getattr(xla_bridge, "_default_backend", None) is not None
    except Exception:  # noqa: BLE001 — private API moved: assume down
        return False


def read_memory_stats(dev) -> Optional[dict]:
    """{bytes_in_use, peak_bytes_in_use, bytes_limit} for one jax
    device (whatever subset its backend reports), or None where the
    backend lacks stats — jax's TFRT CPU devices return None from
    `memory_stats()`, so the cpu tier-1 runs take the graceful
    no-stats path by construction."""
    try:
        ms = dev.memory_stats()
    except Exception:  # noqa: BLE001 — older plugins raise instead
        return None
    if not isinstance(ms, dict):
        return None
    out = {}
    for k in _STAT_KEYS:
        v = ms.get(k)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[k] = int(v)
    return out or None


class DeviceMonitor:
    """Per-device memory/health sampler over the existing poll
    cadences. Thread-safe: streamed fan-out workers and the batched
    poll loop share one ambient monitor, and concurrent searches each
    bracket their own `mark()`/`measured()` window.

    `devices` pins an explicit device list (tests use fakes with a
    `memory_stats()` dict); the default reads `jax.local_devices()`
    — but ONLY when a backend is already up (`_backend_up`), so the
    monitor can never trigger (or hang on) a backend init."""

    def __init__(self, enabled: bool = True, devices=None,
                 min_interval_s: float = MIN_INTERVAL_S):
        self.enabled = bool(enabled)
        self._devices = list(devices) if devices is not None else None
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        self._last: dict = {}       # label -> last per-device stat
        self._order: list = []      # stable label order
        self._peak_seen: dict = {}  # label -> max bytes_in_use sampled
        self._marks: list = []      # open measurement windows
        self._polls = 0
        self._last_t = 0.0

    # -- device list --------------------------------------------------
    def _device_list(self) -> list:
        if self._devices is not None:
            return self._devices
        if not _backend_up():
            return []
        try:
            import jax
            return jax.local_devices()
        except Exception:  # noqa: BLE001 — a torn backend never
            return []      # breaks the instrumented loop

    # -- sampling -----------------------------------------------------
    def sample(self, where: str = "poll", force: bool = False,
               mx=None) -> list:
        """One poll over every local device. Returns the per-device
        stat dicts ([] when disabled, deviceless, or throttled) and
        records them into the ambient metrics registry (`hbm` series
        per stats-reporting device + one `device_poll` point). The
        throttle keeps sub-`min_interval_s` poll loops from turning a
        memory series into noise; `force=True` (mark/measured
        boundaries) always samples."""
        if not self.enabled:
            return []
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_t < self.min_interval_s:
                return []
            self._last_t = now
        devs = self._device_list()
        if not devs:
            return []
        from . import fleet as _fleet
        stats = []
        for i, d in enumerate(devs):
            label = _fleet.device_label(d)
            ms = read_memory_stats(d)
            stat = {"device": label, "index": i,
                    "kind": getattr(d, "device_kind", None),
                    "stats": ms is not None}
            if ms:
                stat.update(ms)
            stats.append(stat)
        with self._lock:
            self._polls += 1
            for stat in stats:
                label = stat["device"]
                if label not in self._last:
                    self._order.append(label)
                self._last[label] = stat
                biu = stat.get("bytes_in_use")
                if biu is not None:
                    self._peak_seen[label] = max(
                        self._peak_seen.get(label, 0), biu)
                    for mk in self._marks:
                        w = mk["win_max"]
                        w[label] = max(w.get(label, 0), biu)
        self._record(stats, where, mx=mx)
        return stats

    def _record(self, stats: list, where: str, mx=None) -> None:
        from . import metrics as _metrics
        mx = mx if mx is not None else _metrics.get_default()
        if not mx.enabled:
            return
        avail = [s for s in stats if s["stats"]]
        series = mx.series(
            "hbm", "per-device memory accounting sampled at existing "
                   "poll boundaries (bytes_in_use / peak / limit)")
        for s in avail:
            # the linted point schema requires bytes_in_use — a
            # backend reporting only exotic stat keys stays in the
            # device_poll envelope, never a malformed series point
            if s.get("bytes_in_use") is not None:
                series.append(dict(s))
        mx.series(
            "device_poll",
            "one point per device-observatory poll: where it sampled "
            "and how many devices reported stats").append({
                "where": str(where),
                "n_devices": len(stats),
                "stats_available": len(avail),
                "bytes_in_use_total": sum(
                    s.get("bytes_in_use") or 0 for s in avail),
            })
        mx.counter("device_polls_total",
                   "device-observatory sampling polls").inc(
            where=str(where))

    # -- measurement windows ------------------------------------------
    def mark(self, where: str = "mark") -> Optional[dict]:
        """Open a measurement window (sampling once, unthrottled):
        the returned token accumulates each device's max bytes_in_use
        over later samples until `measured()` closes it. None when
        disabled — callers keep a `None` token and skip `measured`."""
        if not self.enabled:
            return None
        self.sample(where=where, force=True)
        with self._lock:
            token = {
                "t0": time.monotonic(),
                "polls0": self._polls,
                "peak0": {lb: (self._last[lb].get("peak_bytes_in_use"))
                          for lb in self._order},
                "win_max": {lb: (self._last[lb].get("bytes_in_use")
                                 or 0)
                            for lb in self._order
                            if self._last[lb]["stats"]},
            }
            self._marks.append(token)
            del self._marks[:-64]  # bounded: leaked windows expire
        return token

    def measured(self, token: Optional[dict],
                 where: str = "measured") -> dict:
        """Close a window: one final sample, then the per-window HBM
        block. Per device, `peak_measured` is the allocator's own
        `peak_bytes_in_use` when it GREW inside the window (the new
        high belongs to this window), else the max `bytes_in_use`
        observed at the window's samples — a sampled lower bound,
        honest about being one. Without stats (cpu tier-1) the block
        is the explicit `stats_unavailable` marker."""
        if not self.enabled or token is None:
            return {"schema": 1, "stats_available": False,
                    "stats_unavailable": True, "peak_measured": None,
                    "devices": {}, "samples": 0}
        self.sample(where=where, force=True)
        with self._lock:
            with contextlib.suppress(ValueError):
                self._marks.remove(token)
            devices: dict = {}
            peaks: list = []
            for label in self._order:
                last = self._last.get(label) or {}
                if not last.get("stats"):
                    continue
                peak0 = token["peak0"].get(label)
                peak_now = last.get("peak_bytes_in_use")
                win = token["win_max"].get(
                    label, last.get("bytes_in_use") or 0)
                if peak_now is not None and (peak0 is None
                                             or peak_now > peak0):
                    pm = max(peak_now, win)
                else:
                    pm = win
                devices[label] = {
                    "bytes_in_use": last.get("bytes_in_use"),
                    "peak_bytes_in_use": peak_now,
                    "bytes_limit": last.get("bytes_limit"),
                    "peak_measured": int(pm),
                }
                peaks.append(int(pm))
            # samples taken INSIDE this window — the lifetime poll
            # count would overstate a short window's coverage by
            # whatever the monitor did before it
            samples = self._polls - int(token.get("polls0", 0))
        out = {"schema": 1,
               "stats_available": bool(devices),
               "peak_measured": max(peaks) if peaks else None,
               "devices": devices,
               "samples": samples}
        if not devices:
            out["stats_unavailable"] = True
        return out

    # -- readers ------------------------------------------------------
    def snapshot(self) -> dict:
        """The `/status.json` `hbm` block: last per-device stats, the
        run-wide sampled peaks, and how much of the fleet actually
        reports stats."""
        with self._lock:
            devices = {}
            for label in self._order:
                last = dict(self._last.get(label) or {})
                last.pop("device", None)
                ps = self._peak_seen.get(label)
                if ps is not None:
                    last["peak_seen"] = ps
                    limit = last.get("bytes_limit")
                    if limit:
                        last["utilization"] = round(
                            (last.get("bytes_in_use") or 0) / limit, 4)
                devices[label] = last
            avail = sum(1 for d in devices.values() if d.get("stats"))
            peaks = [d["peak_seen"] for d in devices.values()
                     if d.get("peak_seen") is not None]
            return {"active": bool(self.enabled and self._polls),
                    "polls": self._polls,
                    "n_devices": len(devices),
                    "stats_available": avail,
                    "peak_seen_bytes": max(peaks) if peaks else None,
                    "devices": devices}


def drift_x(measured, predicted) -> Optional[float]:
    """measured / predicted, guarded — the ONE place the HBM drift
    ratio is computed (bench preflight blocks + the regression gate
    share it, so the flag and the printed number can't disagree)."""
    if not measured or not predicted:
        return None
    return round(float(measured) / float(predicted), 4)


def drift_regressed(ratio: Optional[float],
                    threshold: float = HBM_DRIFT_X) -> bool:
    """Is a measured-vs-predicted ratio outside the gate, either way?"""
    if ratio is None:
        return False
    return ratio > threshold or ratio < 1.0 / threshold


def measured_bytes_limit() -> Optional[int]:
    """The chip's own reported HBM capacity: min `bytes_limit` across
    stats-reporting local devices (min — a plan must fit the SMALLEST
    chip it may land on), or None when no device reports one (cpu
    backends, or no backend up yet). Reads the ambient monitor's
    device list when one is installed (tests pin fakes through it);
    otherwise peeks at jax directly, init-safe via `_backend_up`."""
    mon = get_default()
    if mon.enabled:
        devs = mon._device_list()
    else:
        if not _backend_up():
            return None
        try:
            import jax
            devs = jax.local_devices()
        except Exception:  # noqa: BLE001
            return None
    limits = []
    for d in devs:
        ms = read_memory_stats(d)
        if ms and ms.get("bytes_limit"):
            limits.append(int(ms["bytes_limit"]))
    return min(limits) if limits else None


def multichip_record(name: str, n_devices: int, results: list,
                     wall_s: float, hbm: Optional[dict] = None,
                     platform: Optional[str] = None,
                     extra: Optional[dict] = None) -> dict:
    """A `kind="multichip"` ledger record from one mesh dryrun
    section: n_devices, the verdict roll-up, per-device key counts /
    wall from the shard blocks the batched path already stamps, and
    the measured HBM block. Pure dict construction (testable without
    a mesh); `__graft_entry__.dryrun_multichip` banks one per section
    so `/runs` aggregates and `regressions()` cover mesh rounds, not
    just bench."""
    per_device: dict = {}
    verdicts: dict = {}
    for r in results or []:
        if not isinstance(r, dict):
            continue
        v = r.get("valid?")
        key = ("true" if v is True else "false" if v is False
               else str(v))
        verdicts[key] = verdicts.get(key, 0) + 1
        shard = r.get("shard") or {}
        dev = str(shard.get("device", "host"))
        d = per_device.setdefault(dev, {"keys": 0, "wall_s": 0.0})
        d["keys"] += 1
        d["wall_s"] = round(d["wall_s"]
                            + float(shard.get("wall_s") or 0.0), 4)
    rec = {"kind": "multichip", "name": str(name),
           "n_devices": int(n_devices),
           # empty sections verified nothing: "unknown", never a
           # vacuous pass in /runs aggregates
           "verdict": (True if verdicts and set(verdicts) <= {"true"}
                       else False if "false" in verdicts
                       else "unknown"),
           "verdicts": verdicts,
           "wall_s": round(float(wall_s), 4),
           "per_device": per_device}
    if platform is not None:
        rec["platform"] = str(platform)
    if hbm is not None:
        rec["hbm"] = {k: hbm.get(k) for k in
                      ("peak_measured", "stats_available",
                       "stats_unavailable", "devices")
                      if hbm.get(k) is not None}
    if extra:
        rec.update(extra)
    return rec


NULL_MONITOR = DeviceMonitor(enabled=False)


def snapshot() -> dict:
    """The ambient monitor's `/status.json` block (inactive stub when
    disabled) — web.status_snapshot's one entry point."""
    return get_default().snapshot()


# -- ambient default ---------------------------------------------------------
# A plain module global (NOT thread-local), like metrics/fleet/ledger:
# streamed workers and engine threads must see the monitor the run
# installed.
_default: DeviceMonitor = (
    DeviceMonitor() if os.environ.get("JEPSEN_TPU_DEVICES", "")
    not in ("", "0") else NULL_MONITOR)


def get_default() -> DeviceMonitor:
    """The ambient DeviceMonitor — NULL_MONITOR unless
    JEPSEN_TPU_DEVICES=1 was set at import or a caller installed one
    (bench.py and core.run do)."""
    return _default


def set_default(mon: Optional[DeviceMonitor]) -> DeviceMonitor:
    global _default
    prev = _default
    _default = mon if mon is not None else NULL_MONITOR
    return prev


@contextlib.contextmanager
def use(mon: DeviceMonitor) -> Iterator[DeviceMonitor]:
    """Scoped ambient monitor (restores the previous on exit)."""
    prev = set_default(mon)
    try:
        yield mon
    finally:
        set_default(prev)
