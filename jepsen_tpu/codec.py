"""Object <-> bytes serialization (jepsen/src/jepsen/codec.clj:9-29).

The reference round-trips EDN text; this framework's store format is
JSON-payload-based (store/format.py), so the codec speaks compact JSON
with the same nil conventions: None encodes to zero bytes, and zero
bytes (or None) decode to None.
"""

from __future__ import annotations

import json
from typing import Any, Optional


def _check_keys(o: Any) -> None:
    """json.dumps silently coerces non-str dict keys (1 -> "1"), which
    would break decode(encode(o)) == o without an error — reject them
    up front instead."""
    if isinstance(o, dict):
        for k, v in o.items():
            if not isinstance(k, str):
                raise TypeError(
                    f"codec.encode: non-string dict key {k!r} would not "
                    "round-trip (json object keys are strings)")
            _check_keys(v)
    elif isinstance(o, (list, tuple)):
        for v in o:
            _check_keys(v)


def encode(o: Any) -> bytes:
    """Serialize an object to bytes (codec.clj:9-16). Non-JSON-native
    values — including dicts with non-string keys, which json would
    silently coerce — raise TypeError: silent coercion would break the
    decode(encode(o)) == o round-trip."""
    if o is None:
        return b""
    _check_keys(o)
    return json.dumps(o, separators=(",", ":"), sort_keys=True).encode()


def decode(data: Optional[bytes]) -> Any:
    """Deserialize bytes to an object (codec.clj:18-29)."""
    if data is None or len(data) == 0:
        return None
    return json.loads(bytes(data).decode())
