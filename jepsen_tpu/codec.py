"""Object <-> bytes serialization (jepsen/src/jepsen/codec.clj:9-29).

The reference round-trips EDN text; this framework's store format is
JSON-payload-based (store/format.py), so the codec speaks compact JSON
with the same nil conventions: None encodes to zero bytes, and zero
bytes (or None) decode to None.
"""

from __future__ import annotations

import json
from typing import Any, Optional


def encode(o: Any) -> bytes:
    """Serialize an object to bytes (codec.clj:9-16). Non-JSON-native
    values raise TypeError — silent str() coercion would break the
    decode(encode(o)) == o round-trip."""
    if o is None:
        return b""
    return json.dumps(o, separators=(",", ":"), sort_keys=True).encode()


def decode(data: Optional[bytes]) -> Any:
    """Deserialize bytes to an object (codec.clj:18-29)."""
    if data is None or len(data) == 0:
        return None
    return json.loads(bytes(data).decode())
