"""Control-node persistent cache for expensive artifacts (parity with
jepsen.fs-cache, `jepsen/src/jepsen/fs_cache.clj:1-278`): cache values
live under logical paths (tuples of strings/ints/bools), stored as
strings, JSON data, or files, with atomic writes and per-path locks —
used to snapshot e.g. pre-joined cluster state between runs."""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
from contextlib import contextmanager
from typing import Any, Optional, Sequence

DIR = os.path.expanduser("~/.jepsen_tpu/cache")

_locks: dict = {}
_locks_guard = threading.Lock()


def _encode_component(x) -> str:
    """Path components encode to filesystem-safe strings
    (fs_cache.clj Encode protocol, :80-138)."""
    if isinstance(x, bool):
        return f"b-{x}"
    if isinstance(x, int):
        return f"i-{x}"
    if isinstance(x, str):
        safe = "".join(ch if ch.isalnum() or ch in "-_." else "_"
                       for ch in x)
        return f"s-{safe}"
    raise TypeError(f"can't encode cache path component {x!r}")


def fs_path(path: Sequence) -> str:
    assert path, "empty cache path"
    return os.path.join(DIR, *[_encode_component(x) for x in path])


def cached(path: Sequence) -> bool:
    return os.path.exists(fs_path(path))


def clear(path: Optional[Sequence] = None) -> None:
    if path is None:
        shutil.rmtree(DIR, ignore_errors=True)
    else:
        p = fs_path(path)
        if os.path.isdir(p):
            shutil.rmtree(p, ignore_errors=True)
        elif os.path.exists(p):
            os.unlink(p)


def atomic_write(dest: str, writer) -> None:
    """Write via temp file + rename (fs_cache.clj:140-160)."""
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dest))
    try:
        with os.fdopen(fd, "wb") as fh:
            writer(fh)
        os.replace(tmp, dest)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def save_string(path: Sequence, s: str) -> str:
    atomic_write(fs_path(path), lambda fh: fh.write(s.encode()))
    return s


def load_string(path: Sequence) -> Optional[str]:
    try:
        with open(fs_path(path), "rb") as fh:
            return fh.read().decode()
    except FileNotFoundError:
        return None


def save_data(path: Sequence, value: Any) -> Any:
    """JSON analog of save-edn! (fs_cache.clj:213-222)."""
    atomic_write(fs_path(path),
                 lambda fh: fh.write(json.dumps(value).encode()))
    return value


def load_data(path: Sequence) -> Any:
    s = load_string(path)
    return None if s is None else json.loads(s)


def list_data(prefix: Sequence) -> list:
    """Every JSON value cached under a logical path prefix (depth-
    first) — the registry walk `aot.precompile_cached_mesh_plans`
    uses to re-warm all recorded mesh plans after a process restart.
    Unreadable or non-JSON entries are skipped, not raised: a torn
    cache entry must not break warm-up."""
    root = fs_path(prefix)
    out = []
    if os.path.isfile(root):
        try:
            with open(root, "rb") as fh:
                out.append(json.loads(fh.read().decode()))
        except (OSError, ValueError):
            pass
        return out
    for dirpath, _dirs, files in sorted(os.walk(root)):
        for f in sorted(files):
            try:
                with open(os.path.join(dirpath, f), "rb") as fh:
                    out.append(json.loads(fh.read().decode()))
            except (OSError, ValueError):
                continue
    return out


def save_file(path: Sequence, local_file: str) -> str:
    atomic_write(fs_path(path),
                 lambda fh: shutil.copyfileobj(open(local_file, "rb"), fh))
    return local_file


def load_file(path: Sequence) -> Optional[str]:
    p = fs_path(path)
    return p if os.path.exists(p) else None


def save_remote(path: Sequence, remote_path: str) -> str:
    """Download a remote file into the cache (fs_cache.clj:246-258)."""
    from . import control as c
    p = fs_path(path)
    os.makedirs(os.path.dirname(p), exist_ok=True)
    c.download(remote_path, p)
    return remote_path


def deploy_remote(path: Sequence, remote_path: str) -> str:
    """Upload a cached file to the bound node (fs_cache.clj:260-270)."""
    from . import control as c
    p = fs_path(path)
    assert os.path.exists(p), f"nothing cached at {path!r}"
    c.upload(p, remote_path)
    return remote_path


@contextmanager
def locking(path: Sequence):
    """Lock a cache path (fs_cache.clj:272-278)."""
    key = fs_path(path)
    with _locks_guard:
        lock = _locks.setdefault(key, threading.Lock())
    with lock:
        yield
