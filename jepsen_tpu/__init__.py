"""jepsen_tpu — a TPU-native distributed-systems testing framework.

A brand-new framework with the capabilities of Jepsen (reference:
fbarotov/jepsen): orchestrate real database clusters over SSH, drive
randomized concurrent workloads through pure-functional generators while a
nemesis injects faults, record a timestamped operation history, and check
that history against consistency models.

The compute plane — history checking — runs on TPU via JAX: the
Wing–Gong–Lowe linearizability search is implemented as a vmapped,
lockstep frontier exploration over op/process/value tensors
(see `jepsen_tpu.ops.wgl`), and per-key independent sub-histories are
sharded across TPU cores (see `jepsen_tpu.parallel`).

Layer map (mirrors SURVEY.md §1):
  L0  control/        remote execution (ssh / docker / k8s / dummy)
  L1  os_setup, db, net   environment automation
  L2  client, nemesis, generator   workload execution runtime
  L3  core            test orchestration (run())
  L4  checker, independent, ops/   analysis — the TPU plane
  L5  store, web      persistence & observability
  L6  cli             entry points
"""

__version__ = "0.2.0"
