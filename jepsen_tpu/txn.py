"""Transaction micro-op algebra.

Transactions are sequences of *micro-operations* (mops): 3-element
sequences ``[f, k, v]`` where ``f`` is the function ("r", "w", or
"append"), ``k`` the key, and ``v`` the value (``None`` for an
unperformed read).

Capability parity with the in-tree jepsen.txn library
(`txn/src/jepsen/txn.clj:1-75` — reduce-mops, op-mops, ext-reads,
ext-writes, int-write-mops) and `txn/src/jepsen/txn/micro_op.clj`
(f/key/value accessors + read?/write? predicates). Mops here are plain
lists/tuples, not objects: the Elle-equivalent checkers
(`jepsen_tpu.elle`) consume them in bulk and convert to index tensors.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator

R = "r"
W = "w"
APPEND = "append"

_WRITE_FS = (W, APPEND)


# -- micro_op.clj accessors --------------------------------------------------

def mop_f(mop) -> Any:
    return mop[0]


def mop_key(mop) -> Any:
    return mop[1]


def mop_value(mop) -> Any:
    return mop[2]


def is_read(mop) -> bool:
    return mop[0] == R


def is_write(mop) -> bool:
    return mop[0] in _WRITE_FS


def is_mop(mop) -> bool:
    """Is this a legal micro-op? (micro_op.clj:30-35)"""
    return (isinstance(mop, (list, tuple)) and len(mop) == 3
            and mop[0] in (R, W, APPEND))


# -- txn.clj -----------------------------------------------------------------

def reduce_mops(f: Callable, init: Any, history: Iterable) -> Any:
    """Reduce ``f(state, op, mop)`` over every micro-op of every op in
    the history (txn.clj:5-17). Ops are anything with a ``value``
    attribute or key holding the txn."""
    state = init
    for op in history:
        for mop in _txn_of(op):
            state = f(state, op, mop)
    return state


def op_mops(history: Iterable) -> Iterator[tuple]:
    """All (op, mop) pairs from a history, lazily (txn.clj:19-22)."""
    for op in history:
        for mop in _txn_of(op):
            yield op, mop


def ext_reads(txn: Iterable) -> dict:
    """Keys -> values the txn observed *externally* — reads not preceded
    by the txn's own write/read of that key (txn.clj:24-41)."""
    ext: dict = {}
    ignore: set = set()
    for f, k, v in txn:
        if f == R and k not in ignore:
            ext[k] = v
        ignore.add(k)
    return ext


def ext_writes(txn: Iterable) -> dict:
    """Keys -> final values written by the txn (txn.clj:43-54)."""
    ext: dict = {}
    for f, k, v in txn:
        if f != R:
            ext[k] = v
    return ext


def int_write_mops(txn: Iterable) -> dict:
    """Keys -> list of all non-final write mops to that key
    (txn.clj:56-75)."""
    writes: dict = {}
    for mop in txn:
        if mop[0] != R:
            writes.setdefault(mop[1], []).append(mop)
    return {k: vs[:-1] for k, vs in writes.items() if len(vs) > 1}


def _txn_of(op):
    v = getattr(op, "value", None)
    if v is None and isinstance(op, dict):
        v = op.get("value")
    return v or []
