"""Doctor: cross-plane telemetry correlation + automated run diagnosis.

Six recording planes now exist — metrics/trace (PR 2), fleet/RunStatus
(PR 3), the run ledger (PR 7), occupancy/roofline (PR 8), preflight
admission (PR 11), device HBM (PR 12) — but interpreting them was
still manual: the PR-9 `independent_100x2k` regression was root-caused
by a human reading per-bucket compile counts out of the ledger, and
`bench.compute_regressions` can flag *that* a run got slower but never
*why*. The paper's core failure mode is a checker that dies silently
at scale (JVM Knossos "times out" with no attribution); a system built
to fix that should diagnose itself. This module closes the telemetry
into diagnoses:

  * a **rule catalog** D001-D016 over the recorded series and ledger
    records — each rule correlates planes (e.g. D001 joins
    CompileGuard counts against preflight's planned buckets; D005
    joins `fleet_shards` walls into `fleet.summarize`'s rebucket
    hint) and emits ranked, evidence-backed findings: rule id,
    severity, the evidence points (series name + indices + values),
    and a suggested action;
  * a **TelemetryView** that reads ALREADY-RECORDED artifacts only —
    an in-memory metrics registry, exported `*_metrics.jsonl` /
    `*_trace.jsonl` files, ledger records — pure host-side reads:
    zero new compiles, zero new transfers (CompileGuard-proven by
    `scripts/doctor_smoke.py`);
  * surfacing everywhere the planes already surface: `python -m
    jepsen_tpu doctor <run_id|latest|bench>` (`--json`), a `doctor`
    block on `/status.json` and `/runs/<id>.json`, the auto-refreshing
    `/doctor` panel, Perfetto instant-event annotations on the
    offending rounds (`perfetto_instants` -> `trace.to_perfetto`'s
    `instants=`), and a `doctor` metrics series + `kind="doctor"`
    ledger records so findings themselves are queryable and lintable
    (scripts/telemetry_lint.py);
  * `bench.py` runs the doctor over every round and prints the top
    finding on the compact line whenever `compute_regressions` flags
    one — the PR-9 manual triage, automated.

Rule catalog (doc/OBSERVABILITY.md "Diagnosis plane"):

  D001 compile-storm           XLA compiles >> planned shape buckets
                               (the PR-9 per-key warm-up signature)
  D002 fill-collapse           frontier fill far below
                               occupancy.TARGET_FILL
  D003 ladder-thrash           adaptive ladder oscillating between
                               buckets (`wgl_adapt` / util.adapt)
  D004 hbm-drift               measured HBM peak outside
                               devices.HBM_DRIFT_X of the prediction
  D005 straggler-skew          device work skew past
                               fleet.REBUCKET_SKEW_X, rebucket_hint
                               attached as the remedy
  D006 stall                   watchdog declared a source stalled
  D007 route-mismatch          the routed engine measured slower than
                               the alternative it beat on paper
  D008 dominant-phase-shift    the run's dominant trace phase moved
                               vs prior same-platform rounds
  D009 preflight-misprediction degraded admission that ran fine
  D010 oracle-fallback-burst   the host oracle deciding keys the
                               device engine declined
  D011 slo-burn                an SLO error budget burning past the
                               multi-window gate; evidence names the
                               slowest requests' phase walls and the
                               remedy their dominant phase
  D012 queue-backlog           service admission-queue depth growing;
                               warm-hit rate splits the diagnosis
                               (warm -> capacity, cold -> compile
                               storm, cross-linking D001)
  D013 replica-down            a fleet replica's heartbeat stream
                               went silent past its own cadence
                               (evaluated by observatory.py over the
                               federated view, not by `diagnose`)
  D014 replica-skew            cross-replica load / warm-rate skew —
                               the router-affinity oracle for ROADMAP
                               item 2 (observatory.py)
  D015 warm-divergence         a bucket warm on one live replica but
                               missing from another's warm registry —
                               the steal/rewarm signal
                               (observatory.py)
  D016 lock-contention         a witnessed lock's acquire-wait p95
                               (analysis/lockwatch.py `lockwatch`
                               series, JEPSEN_TPU_LOCKWATCH=1) past
                               the contention gate — the remedy names
                               the lock to split or the blocking call
                               to hoist (threadlint T003)

Thresholds are single-sourced from the planes that own them
(`occupancy.TARGET_FILL`, `devices.HBM_DRIFT_X` via `drift`,
`fleet.REBUCKET_SKEW_X`, `slo.burn_threshold`); the doctor-only knobs
live here as module constants.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from . import drift, fleet
from . import ledger as ledger_mod

RULES = {
    "D001": "compile-storm",
    "D002": "fill-collapse",
    "D003": "ladder-thrash",
    "D004": "hbm-drift",
    "D005": "straggler-skew",
    "D006": "stall",
    "D007": "route-mismatch",
    "D008": "dominant-phase-shift",
    "D009": "preflight-misprediction",
    "D010": "oracle-fallback-burst",
    "D011": "slo-burn",
    "D012": "queue-backlog",
    # fleet rules: evaluated by observatory.py over the FEDERATED view
    # (they need N replicas' ledgers, which a single-process
    # TelemetryView never has), registered here so findings, lint and
    # the autopilot share ONE rule catalog
    "D013": "replica-down",
    "D014": "replica-skew",
    "D015": "warm-divergence",
    "D016": "lock-contention",
}

# Rules `diagnose` itself evaluates (single-process planes); the
# fleet rules above are observatory.py's.
LOCAL_RULES = tuple(f"D{i:03d}" for i in range(1, 13)) + ("D016",)

SEVERITIES = ("critical", "warn", "info")
_SEVERITY_RANK = {"critical": 3, "warn": 2, "info": 1}

# D001: fire when total compiles exceed this multiple of the planned
# bucket count AND the absolute floor (a healthy cold run legitimately
# compiles one kernel per ladder bucket; a storm is per-KEY compiles).
COMPILE_STORM_X = 3.0
COMPILE_STORM_MIN = 8

# D002: "collapse" is fill below this fraction of the tracked target
# (below-target-but-working fills are the occupancy report's business;
# the doctor flags lanes running mostly EMPTY), over at least
# MIN_ROUNDS rounds so a 3-round search can't false-positive.
FILL_COLLAPSE_FRAC = 0.5
MIN_ROUNDS = 8

# D003: a bucket re-entered this many times is thrash (the policy's
# hysteresis burns an abandoned bucket once; repeated revisits mean
# the wavefront is defeating it).
THRASH_REVISITS = 2

# D007: the routed engine measured slower than the alternative by
# this factor before the router's call counts as a mismatch.
ROUTE_MISMATCH_X = 1.2

# D008: only a phase that actually dominates (this share of the total
# traced wall) can "shift" — minor phases reshuffle freely.
PHASE_SHIFT_SHARE = 0.35

# D010: oracle fallbacks below this count / fraction of keys are
# normal attrition, not a burst.
FALLBACK_BURST_MIN = 3
FALLBACK_BURST_FRAC = 0.25

# D011: how many of the slowest service requests anchor the
# dominant-phase evidence.
SLO_SLOW_REQUESTS = 3

# D012: queue depth must be observed over this many service points,
# grow by at least this much, and end at its window peak before a
# backlog is declared; the warm-hit rate above the split means the
# pool is warm (capacity problem), below it cold (compile storm).
QUEUE_BACKLOG_MIN_POINTS = 6
QUEUE_BACKLOG_GROWTH = 4
QUEUE_WARM_SPLIT = 0.6

# D016: a lock's acquire-wait p95 (from the lockwatch witness series)
# must clear both the absolute gate and the sample floor before it
# counts as contention — brief spikes on a handful of acquires are
# scheduling noise, not a hot lock.
LOCK_CONTENTION_MIN_POINTS = 8
LOCK_CONTENTION_WAIT_P95_S = 0.005

# Series the view pulls from a registry / metrics JSONL export.
SERIES_OF_INTEREST = (
    "wgl_rounds", "wgl_chunks", "wgl_adapt", "wgl_batched_lanes",
    "fleet_shards", "fleet_faults", "watchdog_stalls", "hbm",
    "preflight", "service", "slo", "lockwatch")

# Bounds on what rides a finding (the full series stay in their
# artifacts; evidence is for pointing, not re-exporting).
MAX_EVIDENCE_POINTS = 16
MAX_FINDINGS_LEDGER = 16


def _target_fill() -> float:
    """occupancy.TARGET_FILL without importing the kernel modules at
    doctor-import time (occupancy pulls in the jitted kernels; the
    doctor must stay importable for pure artifact reads)."""
    try:
        from .occupancy import TARGET_FILL
        return TARGET_FILL
    except Exception:  # noqa: BLE001 — kernels unimportable: the
        return 0.8     # documented default stands in


def finding(rule: str, severity: str, summary: str, *,
            evidence: Optional[list] = None,
            action: Optional[str] = None,
            subject: Optional[str] = None,
            score: float = 1.0,
            remedy: Optional[dict] = None) -> dict:
    """One diagnosis finding. `evidence` entries are
    `{"series": <where>, "field": <what>, "indices": [...],
    "values": [...]}` (+ optional `t` stamps for the Perfetto
    annotations); `remedy` carries a structured fix (e.g. the
    fleet rebucket_hint) next to the human `action` string."""
    assert rule in RULES, f"unknown rule {rule!r}"
    assert severity in SEVERITIES, f"unknown severity {severity!r}"
    out = {"rule": rule, "name": RULES[rule], "severity": severity,
           "summary": str(summary),
           "score": round(float(score), 4),
           "evidence": list(evidence or [])}
    if subject is not None:
        out["subject"] = str(subject)
    if action:
        out["action"] = str(action)
    if remedy:
        out["remedy"] = remedy
    return out


def evidence(series: str, field: str, indices: list, values: list,
             t: Optional[list] = None, **extra) -> dict:
    """One evidence entry, bounded to MAX_EVIDENCE_POINTS."""
    out = {"series": str(series), "field": str(field),
           "indices": list(indices)[:MAX_EVIDENCE_POINTS],
           "values": list(values)[:MAX_EVIDENCE_POINTS]}
    if t:
        out["t"] = [float(x) for x in t[:MAX_EVIDENCE_POINTS]]
    out.update(extra)
    return out


# ---------------------------------------------------------------------------
# TelemetryView — uniform reads over already-recorded artifacts
# ---------------------------------------------------------------------------

class TelemetryView:
    """What one diagnosis looks at: metric series points, ledger
    records, trace spans, and named result/config dicts — all
    already-recorded host-side data (the doctor never executes
    anything on a device).

    `results` maps a subject name to a result-shaped dict (a bench
    config entry, an analysis result, or a ledger record — the rules
    read the overlapping fields: `util`, `preflight`, `hbm`,
    `compiles`, engine/route fields, paired engine rows).
    `prior_phases` carries `{"platform", "dominant"}` entries from
    prior diagnoses (kind="doctor" ledger records) for D008."""

    def __init__(self, *, target: str = "run",
                 platform: Optional[str] = None,
                 series: Optional[dict] = None,
                 records: Optional[list] = None,
                 spans: Optional[list] = None,
                 results: Optional[dict] = None,
                 prior_phases: Optional[list] = None):
        self.target = str(target)
        self.platform = platform
        self._series = {k: list(v) for k, v in (series or {}).items()}
        self.records = [r for r in (records or [])
                        if isinstance(r, dict)]
        self.spans = [s for s in (spans or []) if isinstance(s, dict)]
        self.results = {str(k): v for k, v in (results or {}).items()
                        if isinstance(v, dict)}
        self.prior_phases = [p for p in (prior_phases or [])
                             if isinstance(p, dict)]

    def series(self, name: str) -> list:
        return self._series.get(name, [])


def view_from_registry(reg, **kw) -> TelemetryView:
    """A view over a live metrics Registry (plus whatever records /
    results / spans the caller passes through)."""
    series = {}
    for name in SERIES_OF_INTEREST:
        pts = reg.series(name).points
        if pts:
            series[name] = pts
    kw.setdefault("series", series)
    return TelemetryView(**kw)


def load_series_jsonl(path: str) -> dict:
    """{series: [points]} from a metrics JSONL export (the
    `{"type": "sample", "series": ...}` lines; other line types are
    instrument snapshots, not series points)."""
    out: dict = {}
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict) and obj.get("type") == "sample":
                    name = str(obj.get("series"))
                    pt = {k: v for k, v in obj.items()
                          if k not in ("type", "series")}
                    out.setdefault(name, []).append(pt)
    except OSError:
        pass
    return out


def load_spans_jsonl(path: str) -> list:
    """Span dicts from an OTLP-flavored trace.jsonl export."""
    out: list = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict) \
                        and obj.get("startTimeUnixNano") is not None:
                    out.append(obj)
    except OSError:
        pass
    return out


def _prior_phase_records(led: ledger_mod.Ledger,
                         platform: Optional[str],
                         before: Optional[float] = None) -> list:
    """The D008 baseline: dominant-phase entries from prior
    same-platform kind="doctor" records."""
    out = []
    try:
        for rec in led.query(kind="doctor", until=before):
            if platform is not None and rec.get("platform") not in (
                    None, platform):
                continue
            ph = rec.get("phases")
            if isinstance(ph, dict) and ph.get("dominant"):
                out.append({"platform": rec.get("platform"),
                            "dominant": ph["dominant"],
                            "shares": ph.get("shares")})
    except Exception:  # noqa: BLE001 — a torn ledger yields no
        pass           # baseline, never a failed diagnosis
    return out


def bench_view(root: str, *, registry=None, tracer=None,
               details: Optional[dict] = None,
               since: Optional[float] = None) -> TelemetryView:
    """The view over a bench round: artifacts/telemetry exports (or
    the live registry/tracer when diagnosing in-process), the
    BENCH_DETAILS.json configs as subjects, and the store ledger's
    records from this round (`since`)."""
    # In-process mode (a live registry/tracer passed): NEVER fall
    # back to the artifact files — they are the PREVIOUS round's
    # exports until this round's emit() overwrites them, and a stale
    # stall/collapse must not be re-reported as this round's. The
    # file path is for the CLI diagnosing a finished round.
    art = os.path.join(root, "artifacts", "telemetry")
    in_process = registry is not None or tracer is not None
    if registry is not None:
        series = {}
        for name in SERIES_OF_INTEREST:
            pts = registry.series(name).points
            if pts:
                series[name] = pts
    elif in_process:
        series = {}
    else:
        series = load_series_jsonl(
            os.path.join(art, "bench_metrics.jsonl"))
    if tracer is not None:
        spans = [sp.to_json() for sp in tracer.spans]
    elif in_process:
        spans = []
    else:
        spans = load_spans_jsonl(os.path.join(art, "bench_trace.jsonl"))
    if details is None:
        try:
            with open(os.path.join(root, "BENCH_DETAILS.json")) as fh:
                details = json.load(fh)
        except (OSError, ValueError):
            details = {}
    results: dict = {}
    platform = details.get("platform")
    headline = {k: details.get(k) for k in
                ("util", "occupancy", "hbm", "preflight", "telemetry")
                if details.get(k) is not None}
    if headline or details.get("verdict") is not None:
        headline["valid?"] = details.get("verdict")
        cg = details.get("compile_guard")
        if isinstance(cg, dict) and isinstance(cg.get("compiles"), int):
            headline["compiles"] = cg["compiles"]
        results[details.get("metric") or "headline"] = headline
    for name, cfg in (details.get("configs") or {}).items():
        if isinstance(cfg, dict):
            results[name] = cfg
    led = ledger_mod.Ledger(os.path.join(root, "store"))
    if since is None:
        # no explicit round boundary (the CLI path): scope to the
        # LATEST round via the kind="bench-round" markers emit()
        # banks — records since the PREVIOUS round's marker belong
        # to the newest round. Pooling many rounds' records would
        # sum their healthy cold compiles into a false D001.
        marks = led.query(kind="bench-round", limit=2,
                          newest_first=True)
        if len(marks) == 2:
            since = marks[1].get("t")
    records = led.query(since=since) if since is not None \
        else led.query(limit=200)
    return TelemetryView(
        target="bench", platform=platform, series=series, spans=spans,
        results=results,
        records=[r for r in records if r.get("kind") != "doctor"],
        prior_phases=_prior_phase_records(led, platform, before=since))


def run_view(store_root: str, run_id: str = "latest") -> TelemetryView:
    """The view over one ledger record (`run_id`, or the newest when
    "latest"): the record as the single subject, plus its exported
    trace artifact when one was recorded."""
    led = ledger_mod.Ledger(store_root)
    if run_id == "latest":
        # newest record that is not itself a diagnosis — the doctor
        # must not end up diagnosing its own prior reports
        rec = next((r for r in led.query(newest_first=True)
                    if r.get("kind") != "doctor"), None)
    else:
        rec = led.get(run_id)
    if rec is None:
        raise KeyError(f"no ledger record {run_id!r} under "
                       f"{store_root!r}")
    spans: list = []
    rel = (rec.get("artifacts") or {}).get("trace")
    if rel:
        spans = load_spans_jsonl(
            os.path.join(store_root, *str(rel).split("/")))
    return TelemetryView(
        target=str(rec.get("id")), platform=rec.get("platform"),
        results={str(rec.get("name") or rec.get("id")): rec},
        records=[rec], spans=spans,
        prior_phases=_prior_phase_records(led, rec.get("platform"),
                                          before=rec.get("t")))


# ---------------------------------------------------------------------------
# shared readers
# ---------------------------------------------------------------------------

def _util(res: dict) -> dict:
    u = res.get("util")
    return u if isinstance(u, dict) else {}


def _pf(res: dict) -> dict:
    pf = res.get("preflight")
    return pf if isinstance(pf, dict) else {}


def phase_profile(spans: list) -> Optional[dict]:
    """{"phases": {name: seconds}, "shares": {name: frac},
    "dominant": name} over finished spans — the per-phase wall
    distribution D008 compares across rounds. None when the trace is
    empty/degenerate."""
    totals: dict = {}
    for sp in spans or []:
        t0, t1 = sp.get("startTimeUnixNano"), sp.get("endTimeUnixNano")
        if t0 is None or t1 is None:
            continue
        dur = (int(t1) - int(t0)) / 1e9
        if dur <= 0:
            continue
        name = str(sp.get("name"))
        totals[name] = totals.get(name, 0.0) + dur
    if not totals:
        return None
    total = sum(totals.values())
    shares = {n: round(v / total, 4) for n, v in totals.items()}
    dominant = max(shares, key=lambda n: shares[n])
    return {"phases": {n: round(v, 4) for n, v in totals.items()},
            "shares": shares, "dominant": dominant,
            "dominant_share": shares[dominant]}


def _bucket_label(shapes: dict) -> str:
    w = shapes.get("W_pad") or shapes.get("W")
    return f"W={w if w is not None else '?'}," \
           f"K={shapes.get('K') if shapes.get('K') is not None else '?'}"


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------

def _d001(view: TelemetryView) -> list:
    """Compile-storm: XLA compiles >> planned shape buckets — the
    PR-9 `independent_100x2k` signature (per-key shape buckets each
    paying a compile inside the measured window; the fix was
    `parallel.shared_shape_bucket`)."""
    per_bucket: dict = {}
    idxs: list = []
    vals: list = []
    subjects: dict = {}
    planned = 0
    # ledger records: per-key/per-config CompileGuard counts, grouped
    # by shape bucket — the evidence a human read by hand in PR 9
    for i, rec in enumerate(view.records):
        c = rec.get("compiles")
        if not isinstance(c, int) or isinstance(c, bool) or c <= 0:
            continue
        bucket = _bucket_label(rec.get("shapes") or {})
        per_bucket[bucket] = per_bucket.get(bucket, 0) + c
        idxs.append(i)
        vals.append(c)
        name = str(rec.get("name") or "?")
        subjects[name] = subjects.get(name, 0) + c
        planned = max(planned,
                      len(_pf(rec).get("buckets") or ()))
    # result/config subjects carrying a compile count directly
    for name, res in view.results.items():
        c = res.get("compiles")
        if not isinstance(c, int) or isinstance(c, bool) or c <= 0:
            continue
        if name in subjects:  # the same run's ledger record
            continue
        bucket = _bucket_label(
            {"K": res.get("K"), "W_pad": res.get("W_pad"),
             "W": res.get("W")})
        per_bucket[bucket] = per_bucket.get(bucket, 0) + c
        subjects[name] = subjects.get(name, 0) + c
        planned = max(planned, len(_pf(res).get("buckets") or ()))
    total = sum(per_bucket.values())
    if not total:
        return []
    # planned compiles: the preflight bucket plan when one exists,
    # else one compile per distinct shape bucket actually seen
    planned = max(planned, len(per_bucket), 1)
    if total < COMPILE_STORM_MIN or total <= COMPILE_STORM_X * planned:
        return []
    top = max(subjects, key=lambda n: subjects[n])
    ev = [evidence("ledger", "compiles", idxs, vals,
                   per_bucket=per_bucket, planned_buckets=planned)]
    return [finding(
        "D001", "critical",
        f"{total} XLA compiles across {len(per_bucket)} shape "
        f"bucket(s) vs {planned} planned — compiles are being paid "
        f"per key/call, not per bucket",
        subject=top, evidence=ev, score=total / planned,
        action="warm every shape bucket before the measured window "
               "(ops/aot.precompile_wgl_ladder / "
               "precompile_elle_closure) or pad keys into one shared "
               "bucket (parallel.shared_shape_bucket — the PR-9 fix)")]


def _d002(view: TelemetryView) -> list:
    """Fill-collapse: frontier fill far below occupancy.TARGET_FILL —
    the lanes run mostly empty and every round wastes the idle
    fraction of its gather bandwidth."""
    target = _target_fill()
    floor = target * FILL_COLLAPSE_FRAC
    out: list = []
    fired_subjects = False
    for name, res in view.results.items():
        util = _util(res)
        fill = util.get("frontier_fill")
        rounds = util.get("rounds")
        if not isinstance(fill, (int, float)) or fill >= floor:
            continue
        if isinstance(rounds, int) and rounds < MIN_ROUNDS:
            continue
        ev = [evidence(f"results:{name}", "util.frontier_fill",
                       [0], [fill], target=target)]
        out.append(finding(
            "D002", "warn",
            f"frontier fill {fill} vs target {target} "
            f"(< {FILL_COLLAPSE_FRAC:.0%} of target)",
            subject=name, evidence=ev,
            score=(target - fill) / max(target, 1e-9),
            action="let the adaptive ladder start lower / verify "
                   "compact-before-expand is on; a beam this sparse "
                   "pays full-K gathers for near-empty lanes "
                   "(ROADMAP item 5)"))
        fired_subjects = True
    pts = view.series("wgl_rounds")
    fills = [(i, p) for i, p in enumerate(pts)
             if isinstance(p.get("fill"), (int, float))]
    if len(fills) >= MIN_ROUNDS:
        mean = sum(p["fill"] for _, p in fills) / len(fills)
        if mean < floor:
            worst = sorted(fills, key=lambda ip: ip[1]["fill"])
            ev = [evidence(
                "wgl_rounds", "fill",
                [i for i, _ in worst], [p["fill"] for _, p in worst],
                t=[p["t"] for _, p in worst if p.get("t") is not None],
                mean_fill=round(mean, 4), target=target)]
            if not fired_subjects:
                out.append(finding(
                    "D002", "warn",
                    f"mean per-round fill {round(mean, 4)} over "
                    f"{len(fills)} recorded rounds vs target {target}",
                    evidence=ev,
                    score=(target - mean) / max(target, 1e-9),
                    action="let the adaptive ladder start lower / "
                           "verify compact-before-expand is on "
                           "(ROADMAP item 5)"))
            elif out:
                # subjects already named: attach the offending rounds
                # (with their wall stamps — the Perfetto annotations)
                out[-1]["evidence"].append(ev[0])
    return out


def _entered_buckets(path: list) -> list:
    """The sequence of buckets ENTERED by an adapt path
    (`[[from_K, to_K, reason], ...]`)."""
    out = []
    for step in path or []:
        if isinstance(step, (list, tuple)) and len(step) >= 2:
            out.append(step[1])
    return out


def _d003(view: TelemetryView) -> list:
    """Ladder-thrash: the adaptive scheduler oscillating between
    buckets — each switch pays a frontier migration and a warm-cache
    dispatch, so a wavefront that defeats the hysteresis shows up as
    re-entered buckets."""
    out: list = []
    for name, res in view.results.items():
        adapt = _util(res).get("adapt")
        if not isinstance(adapt, dict):
            continue
        entered = _entered_buckets(adapt.get("path"))
        revisits = len(entered) - len(set(entered))
        if revisits < THRASH_REVISITS:
            continue
        ev = [evidence(f"results:{name}", "util.adapt.path",
                       list(range(len(entered))), entered,
                       switches=adapt.get("switches"))]
        out.append(finding(
            "D003", "warn",
            f"{adapt.get('switches')} ladder switches with "
            f"{revisits} bucket revisit(s) ({entered})",
            subject=name, evidence=ev, score=revisits,
            action="pin frontier=K for this shape or widen the "
                   "policy hysteresis (ops/adapt.Policy); a thrashing "
                   "ladder pays migration + dispatch per switch"))
    if not out:
        # series fallback: wgl_adapt points carry no search id, and a
        # fan-out round interleaves MANY searches' switches — so
        # segment on the per-search `chunk` counter resetting (each
        # search's chunks increase monotonically; a new key restarts
        # low). Revisits only count WITHIN one segment: N keys each
        # escalating once to the same bucket is healthy, not thrash.
        pts = [p for p in view.series("wgl_adapt")
               if p.get("to_K") is not None]
        segments: list = []
        prev_chunk = None
        prev_to = None
        for p in pts:
            chunk = p.get("chunk")
            # one search's switches CHAIN: its next from_K is its
            # last to_K, and its chunk counter only grows. A break
            # in either is another search's point (three keys each
            # escalating 16->32 at chunks 2,3,4 produce three
            # one-point segments, not one fake-thrash segment).
            fresh = (chunk is None or prev_chunk is None
                     or chunk <= prev_chunk
                     or p.get("from_K") != prev_to)
            if fresh:
                segments.append([])
            segments[-1].append(p)
            prev_chunk = chunk
            prev_to = p.get("to_K")
        worst: list = []
        revisits = 0
        for seg in segments:
            entered = [p["to_K"] for p in seg]
            r = len(entered) - len(set(entered))
            if r > revisits:
                revisits, worst = r, seg
        if revisits >= THRASH_REVISITS:
            entered = [p["to_K"] for p in worst]
            ev = [evidence("wgl_adapt", "to_K",
                           [pts.index(p) for p in worst], entered,
                           t=[p["t"] for p in worst
                              if p.get("t") is not None])]
            out.append(finding(
                "D003", "warn",
                f"{len(entered)} ladder switches in one search with "
                f"{revisits} bucket revisit(s) ({entered})",
                evidence=ev, score=revisits,
                action="pin frontier=K for this shape or widen the "
                       "policy hysteresis (ops/adapt.Policy)"))
    return out


def _d004(view: TelemetryView) -> list:
    """HBM-drift: the measured device peak outside
    devices.HBM_DRIFT_X of preflight's analytic prediction — an
    under-prediction admits plans that OOM, an over-prediction wastes
    admission capacity."""
    out: list = []
    for name, res in view.results.items():
        pf = _pf(res)
        ratio = pf.get("hbm_drift_x")
        measured = pf.get("hbm_peak_measured")
        predicted = pf.get("hbm_peak_bytes")
        if not isinstance(ratio, (int, float)):
            hbm = res.get("hbm")
            if isinstance(hbm, dict):
                measured = hbm.get("peak_measured")
            ratio = drift.drift_x(measured, predicted)
        if ratio is None or not drift.drift_regressed(ratio):
            continue
        under = ratio > 1.0  # measured > predicted
        ev = [evidence(f"results:{name}", "preflight.hbm_drift_x",
                       [0], [ratio], measured=measured,
                       predicted=predicted,
                       threshold_x=drift.HBM_DRIFT_X)]
        out.append(finding(
            "D004", "warn" if under else "info",
            f"measured HBM peak is {ratio}x the admission "
            f"prediction (gate: {drift.HBM_DRIFT_X}x either way)",
            subject=name, evidence=ev,
            score=max(ratio, 1.0 / max(ratio, 1e-9)),
            action=("the analytic byte model under-predicts — an "
                    "admitted plan can OOM; recalibrate "
                    "analysis/preflight's peak model" if under else
                    "the analytic byte model over-predicts — "
                    "admission capacity is being left idle; "
                    "recalibrate analysis/preflight's peak model")))
    return out


def _d005(view: TelemetryView) -> list:
    """Straggler-skew: one device carrying the fan-out — a lockstep
    mesh pays the busiest device's wall, and fleet.rebucket_hint
    names exactly which keys to move (the remedy rides the
    finding)."""
    out: list = []
    for name, res in view.results.items():
        fl = _util(res).get("fleet")
        if not isinstance(fl, dict):
            continue
        skew = fl.get("work_skew")
        if not isinstance(skew, (int, float)) \
                or skew <= fleet.REBUCKET_SKEW_X:
            continue
        devs = fl.get("devices") or {}
        labels = sorted(devs)
        ev = [evidence(f"results:{name}", "util.fleet.work_skew",
                       [0], [skew],
                       per_device_wall={d: (devs[d] or {}).get("wall_s")
                                        for d in labels})]
        out.append(finding(
            "D005", "warn",
            f"work skew {skew}x across "
            f"{fl.get('device_count') or len(labels)} device(s) — "
            f"the mesh pays the busiest device's wall",
            subject=name, evidence=ev, score=skew,
            remedy=fl.get("rebucket_hint"),
            action="apply the rebucket hint (move the named keys to "
                   "the lazy device) or work-steal between polls "
                   "(fleet.summarize — ROADMAP item 2)"))
    if not out:
        shards = view.series("fleet_shards")
        if len(shards) >= 4:
            summ = fleet.summarize(shards)
            skew = summ.get("work_skew")
            if isinstance(skew, (int, float)) \
                    and skew > fleet.REBUCKET_SKEW_X \
                    and summ.get("device_count", 0) >= 2:
                devs = summ.get("devices") or {}
                ev = [evidence(
                    "fleet_shards", "wall_s",
                    list(range(min(len(shards),
                                   MAX_EVIDENCE_POINTS))),
                    [s.get("wall_s") for s in
                     shards[:MAX_EVIDENCE_POINTS]],
                    work_skew=skew,
                    per_device_wall={d: v.get("wall_s")
                                     for d, v in devs.items()})]
                out.append(finding(
                    "D005", "warn",
                    f"work skew {skew}x across "
                    f"{summ.get('device_count')} device(s)",
                    evidence=ev, score=skew,
                    remedy=summ.get("rebucket_hint"),
                    action="apply the rebucket hint or work-steal "
                           "between polls (ROADMAP item 2)"))
    return out


def _d006(view: TelemetryView) -> list:
    """Stall: the watchdog declared a source dead — the one failure
    the paper's reference checkers hide (a timeout with nothing to
    show)."""
    pts = view.series("watchdog_stalls")
    out: list = []
    if pts:
        ev = [evidence("watchdog_stalls", "age_s",
                       list(range(len(pts))),
                       [p.get("age_s") for p in pts],
                       t=[p["t"] for p in pts
                          if p.get("t") is not None],
                       sources=sorted({str(p.get("source"))
                                       for p in pts}))]
        out.append(finding(
            "D006", "critical",
            f"{len(pts)} watchdog stall(s): "
            f"{sorted({str(p.get('source')) for p in pts})}",
            evidence=ev, score=10 + len(pts),
            action="inspect the stalled source's last beat payload; "
                   "JEPSEN_TPU_WATCHDOG_ESCALATION=cancel reclaims "
                   "the budget with a partial verdict"))
        return out
    for name, res in view.results.items():
        stall = res.get("stall")
        stalls = res.get("stalls")
        if not isinstance(stall, dict) and not (
                isinstance(stalls, int) and stalls > 0):
            continue
        ev = [evidence(f"results:{name}", "stalls", [0],
                       [stalls if isinstance(stalls, int) else 1])]
        out.append(finding(
            "D006", "critical",
            "the run recorded a watchdog stall",
            subject=name, evidence=ev, score=10,
            action="inspect the stalled source's last beat payload "
                   "(doc/OBSERVABILITY.md \"Stall watchdog\")"))
    return out


_ROW_PAIRS = (
    # (routed row, alternative row, engines that mean "the routed
    #  row is the device-side choice")
    ("closure_row", "host_row"),
    ("device_row", "oracle_row"),
)


def _d007(view: TelemetryView) -> list:
    """Route-mismatch: the router's choice measured slower than the
    alternative it declined — the route REASON disagrees with the
    measured engine wall."""
    out: list = []
    for name, res in view.results.items():
        reason = res.get("cycle-route-reason") or res.get(
            "route_reason")
        for routed_key, alt_key in _ROW_PAIRS:
            routed = res.get(routed_key)
            alt = res.get(alt_key)
            if not isinstance(routed, dict) or not isinstance(
                    alt, dict):
                continue
            rw, aw = routed.get("wall_s"), alt.get("wall_s")
            if not isinstance(rw, (int, float)) or not isinstance(
                    aw, (int, float)) or aw <= 0:
                continue
            # only decided alternatives count: beating a DNF row is
            # exactly what the router is for
            if alt.get("verdict") in (None, "unknown"):
                continue
            if rw <= ROUTE_MISMATCH_X * aw:
                continue
            ev = [evidence(f"results:{name}", "wall_s", [0, 1],
                           [rw, aw], rows=[routed_key, alt_key],
                           route_reason=reason)]
            out.append(finding(
                "D007", "warn",
                f"routed engine ran {round(rw / aw, 2)}x slower than "
                f"the declined alternative ({routed_key} {rw}s vs "
                f"{alt_key} {aw}s; route reason: {reason})",
                subject=name, evidence=ev, score=rw / aw,
                action="re-derive the route cost model "
                       "(ops/route.py) against this shape — the "
                       "work model mispriced one engine"))
        pf = _pf(res)
        if pf.get("engine_match") is False:
            ev = [evidence(f"results:{name}", "preflight.engine_match",
                           [0], [False], planned=pf.get("engine"),
                           ran=res.get("engine")
                           or res.get("cycle-engine"))]
            out.append(finding(
                "D007", "info",
                f"preflight planned engine {pf.get('engine')!r} but "
                f"{res.get('engine') or res.get('cycle-engine')!r} "
                "ran",
                subject=name, evidence=ev, score=1,
                action="the static route mirror drifted from the "
                       "runtime router — re-align "
                       "analysis/preflight.plan_elle/plan_wgl"))
    return out


def _d008(view: TelemetryView) -> list:
    """Dominant-phase-shift: the run's cost center moved vs prior
    same-platform rounds (e.g. encode suddenly dominating a search
    that used to be device-round-bound)."""
    prof = phase_profile(view.spans)
    if not prof or len(prof["shares"]) < 2:
        return []
    priors = [p for p in view.prior_phases
              if view.platform is None or p.get("platform") in
              (None, view.platform)]
    doms = [p.get("dominant") for p in priors if p.get("dominant")]
    if not doms:
        return []
    # the modal prior dominant: one odd round must not become the
    # baseline the next round "shifts" from
    prior_dom = max(set(doms), key=doms.count)
    cur = prof["dominant"]
    if cur == prior_dom or prof["dominant_share"] < PHASE_SHIFT_SHARE:
        return []
    shares = prof["shares"]
    names = sorted(shares, key=lambda n: -shares[n])
    ev = [evidence("trace", "phase_share",
                   list(range(len(names))),
                   [shares[n] for n in names], phases=names,
                   prior_dominant=prior_dom,
                   prior_rounds=len(doms))]
    return [finding(
        "D008", "info",
        f"dominant trace phase shifted to {cur!r} "
        f"({prof['dominant_share']:.0%} of traced wall) from "
        f"{prior_dom!r} over {len(doms)} prior round(s)",
        evidence=ev, score=prof["dominant_share"],
        action="profile the new dominant phase — the run's cost "
               "center moved, so prior optimizations no longer "
               "target the bottleneck")]


def _d009(view: TelemetryView) -> list:
    """Preflight-misprediction: an admission the analyzer DEGRADED
    ran to a clean verdict anyway — the degrade rules are paying
    conservatism the hardware did not demand."""
    out: list = []
    for name, res in view.results.items():
        pf = _pf(res)
        verdict = res.get("valid?", res.get("verdict"))
        if pf.get("verdict") != "degrade":
            continue
        if verdict not in (True, False):
            continue
        if isinstance(res.get("stall"), dict) or res.get("stalls"):
            continue
        ev = [evidence(f"results:{name}", "preflight.verdict", [0],
                       ["degrade"], run_verdict=verdict,
                       rules=pf.get("rules"))]
        out.append(finding(
            "D009", "info",
            f"admission degraded this run ({pf.get('rules')}) but it "
            f"decided cleanly (verdict={verdict})",
            subject=name, evidence=ev, score=1,
            action="loosen the fired degrade rule's threshold in "
                   "analysis/preflight — this shape runs fine "
                   "undegraded"))
    return out


def _d010(view: TelemetryView) -> list:
    """Oracle-fallback-burst: the host oracle deciding keys the
    device engine declined — every fallback forfeits the device
    speedup, and a burst of them means the device path is broken for
    this shape, not unlucky."""
    out: list = []
    for name, res in view.results.items():
        fl = _util(res).get("fleet")
        if not isinstance(fl, dict):
            continue
        fallbacks, keys = fl.get("fallbacks"), fl.get("keys")
        if not isinstance(fallbacks, int) or not isinstance(keys, int):
            continue
        if fallbacks < FALLBACK_BURST_MIN or keys <= 0 \
                or fallbacks / keys < FALLBACK_BURST_FRAC:
            continue
        ev = [evidence(f"results:{name}", "util.fleet.fallbacks",
                       [0], [fallbacks], keys=keys,
                       frac=round(fallbacks / keys, 4))]
        out.append(finding(
            "D010", "warn",
            f"{fallbacks}/{keys} keys decided by the host oracle "
            "fallback",
            subject=name, evidence=ev, score=fallbacks / keys * 10,
            action="read the per-key device_cause fields on the "
                   "fallback shards — the device engine is declining "
                   "this shape, and the oracle's wall is the bound "
                   "now"))
    if not out:
        shards = view.series("fleet_shards")
        fb = [(i, s) for i, s in enumerate(shards)
              if s.get("engine") == "oracle-fallback"]
        if len(fb) >= FALLBACK_BURST_MIN and shards \
                and len(fb) / len(shards) >= FALLBACK_BURST_FRAC:
            ev = [evidence("fleet_shards", "engine",
                           [i for i, _ in fb],
                           ["oracle-fallback"] * len(fb),
                           keys=len(shards))]
            out.append(finding(
                "D010", "warn",
                f"{len(fb)}/{len(shards)} keys decided by the host "
                "oracle fallback",
                evidence=ev, score=len(fb) / len(shards) * 10,
                action="read the per-key device_cause fields on the "
                       "fallback shards"))
    return out


def _burn_x() -> float:
    """slo.burn_threshold without requiring the slo module at
    diagnosis time (the _target_fill pattern)."""
    try:
        from .slo import burn_threshold
        return burn_threshold()
    except Exception:  # noqa: BLE001
        return 2.0


_PHASE_REMEDY = {
    "queue_wait_s": "queue-wait dominates — add service workers / "
                    "devices, or raise the batch size so same-bucket "
                    "arrivals coalesce harder",
    "warm_s": "warm-dispatch dominates — pre-warm the shape buckets "
              "ahead of traffic (aot.precompile_service_bucket; "
              "Service.rewarm restores the fs_cache plan registry "
              "after a restart)",
    "search_s": "the search itself dominates — this is a kernel "
                "problem, not a serving one; read the occupancy/"
                "roofline planes for the offending shape",
    "preflight_s": "admission analysis dominates — cache the plan "
                   "per shape bucket (analysis/preflight)",
    "admit_s": "request parsing dominates — histories this large "
               "should stream, not POST",
    "respond_s": "response accounting dominates — the ledger write "
                 "path is in the request loop",
}


def _slowest_phases(view: TelemetryView) -> tuple:
    """(evidence entry, dominant phase) over the slowest
    service-request records' phase walls — the D011 anchor. (None,
    None) when no phased requests are recorded."""
    # indices are into view.records (the convention every
    # ledger-evidence rule shares, e.g. _d001) — NOT into the
    # filtered service-request subset, which would dereference
    # unrelated records on a real interleaved ledger
    svc = [(i, r) for i, r in enumerate(view.records)
           if r.get("kind") == "service-request"
           and isinstance(r.get("wall_s"), (int, float))
           and isinstance(r.get("phases"), dict)]
    if not svc:
        return None, None
    svc.sort(key=lambda ir: -float(ir[1]["wall_s"]))
    slow = svc[:SLO_SLOW_REQUESTS]
    totals: dict = {}
    per_req = {}
    for _i, rec in slow:
        per_req[str(rec.get("id"))] = rec["phases"]
        for ph, v in rec["phases"].items():
            if isinstance(v, (int, float)):
                totals[ph] = totals.get(ph, 0.0) + float(v)
    dominant = max(totals, key=lambda p: totals[p]) if totals else None
    ev = evidence("ledger", "wall_s", [i for i, _ in slow],
                  [rec["wall_s"] for _, rec in slow],
                  phases=per_req, dominant_phase=dominant)
    return ev, dominant


def _d011(view: TelemetryView) -> list:
    """SLO-burn: an error budget burning past the multi-window gate
    (slo.Engine's burn alert) — the serving plane's equivalent of a
    wall regression, with the evidence pointing at the slowest
    requests' phase walls and the remedy naming the dominant one."""
    burning: dict = {}
    idxs: list = []
    rates: list = []
    pts = view.series("slo")
    for i, p in enumerate(pts):
        br = p.get("burn_rate")
        if not isinstance(br, (int, float)):
            continue
        if p.get("burn_alert") is True or (
                p.get("met") is False and br > _burn_x()):
            name = str(p.get("objective"))
            if br >= burning.get(name, 0.0):
                burning[name] = br
            idxs.append(i)
            rates.append(br)
    for rec in view.records:
        if rec.get("kind") != "slo":
            continue
        alerted = {str(a) for a in rec.get("burn_alerts") or []}
        for row in rec.get("objectives") or []:
            name = str(row.get("name"))
            br = row.get("burn_rate")
            if name in alerted and isinstance(br, (int, float)):
                burning[name] = max(burning.get(name, 0.0), br)
    if not burning:
        return []
    worst = max(burning.values())
    ev = []
    if idxs:
        ev.append(evidence("slo", "burn_rate", idxs, rates,
                           objectives=sorted(burning)))
    slow_ev, dominant = _slowest_phases(view)
    if slow_ev is not None:
        ev.append(slow_ev)
    action = _PHASE_REMEDY.get(
        dominant,
        "inspect the phase walls on the slowest service-request "
        "records — the burning objective names which wall to cut")
    remedy = {"dominant_phase": dominant} if dominant else None
    return [finding(
        "D011", "warn",
        f"SLO error budget burning: {sorted(burning)} at up to "
        f"{round(worst, 2)}x budget (gate {_burn_x()}x, "
        f"multi-window)",
        subject=",".join(sorted(burning)), evidence=ev, score=worst,
        action=action, remedy=remedy)]


def _d012(view: TelemetryView) -> list:
    """Queue-backlog: the admission queue deepening across service
    completions. A warm pool falling behind is a capacity problem;
    a cold one is paying compiles inside the serve path — the D001
    compile-storm signature arriving through the front door."""
    pts = [p for p in view.series("service")
           if isinstance(p.get("queue_depth"), int)]
    if len(pts) < QUEUE_BACKLOG_MIN_POINTS:
        return []
    window = pts[-12:]
    depths = [p["queue_depth"] for p in window]
    growth = depths[-1] - depths[0]
    rising = sum(1 for a, b in zip(depths, depths[1:]) if b >= a)
    if growth < QUEUE_BACKLOG_GROWTH \
            or depths[-1] != max(depths) \
            or rising < 0.7 * (len(depths) - 1):
        return []
    warm = [bool(p.get("warm_hit")) for p in window]
    warm_rate = sum(warm) / len(warm)
    # backpressure vs backlog: sheds in the window mean the service
    # is ALREADY refusing load (burn-driven admission control) — a
    # deepening queue despite sheds is a capacity deficit, not a
    # missing brake
    sheds = sum(1 for p in window if p.get("shed"))
    base = max(0, len(pts) - len(window))
    idxs = [base + i for i in range(len(window))]
    ev = [evidence("service", "queue_depth", idxs, depths,
                   t=[p["t"] for p in window
                      if p.get("t") is not None],
                   warm_rate=round(warm_rate, 3),
                   shed_count=sheds)]
    if warm_rate >= QUEUE_WARM_SPLIT:
        action = ("the pool is warm but falling behind — add "
                  "service workers / devices, or raise max_batch so "
                  "coalescing amortizes harder (capacity)")
        if sheds:
            action += (f"; {sheds} shed(s) in the window: admission "
                       "is already braking, the deficit is capacity")
    else:
        action = ("cold buckets are paying compiles inside the "
                  "serve path — warm ahead of traffic "
                  "(aot.precompile_service_plan / Service.rewarm)"
                  "; see D001 compile-storm for the kernel-side "
                  "signature")
        ev.append(evidence("service", "warm_hit", idxs,
                           warm, related_rule="D001"))
    return [finding(
        "D012", "warn",
        f"admission queue depth grew {depths[0]} -> {depths[-1]} "
        f"over {len(window)} request(s) at warm-hit rate "
        f"{round(warm_rate, 2)}"
        + (f" with {sheds} shed(s)" if sheds else ""),
        evidence=ev, score=growth, action=action)]


def _d016(view: TelemetryView) -> list:
    """Lock-contention: a witnessed lock's acquire-wait p95 past the
    gate. The lockwatch series only exists under
    JEPSEN_TPU_LOCKWATCH=1, so this rule is silent on uninstrumented
    runs — and a hot lock usually means either too much work under it
    (split the guarded state) or a blocking call that threadlint T003
    should have flagged (hoist it outside the critical section)."""
    pts = [p for p in view.series("lockwatch")
           if p.get("event") == "acquire"
           and isinstance(p.get("wait_s"), (int, float))]
    if not pts:
        return []
    by_lock: dict = {}
    for i, p in enumerate(pts):
        by_lock.setdefault(str(p.get("lock")), []).append(
            (i, float(p["wait_s"])))
    out = []
    for label, rows in sorted(by_lock.items()):
        if len(rows) < LOCK_CONTENTION_MIN_POINTS:
            continue
        waits = sorted(w for _, w in rows)
        p95 = waits[min(len(waits) - 1,
                        int(0.95 * (len(waits) - 1)))]
        if p95 < LOCK_CONTENTION_WAIT_P95_S:
            continue
        hot = sorted(rows, key=lambda r: r[1],
                     reverse=True)[:MAX_EVIDENCE_POINTS]
        out.append(finding(
            "D016", "warn",
            f"lock {label!r} acquire-wait p95 "
            f"{round(p95 * 1e3, 2)}ms over {len(rows)} contended "
            f"acquire(s) (gate "
            f"{LOCK_CONTENTION_WAIT_P95_S * 1e3:g}ms)",
            evidence=[evidence(
                "lockwatch", "wait_s",
                [i for i, _ in hot],
                [round(w, 6) for _, w in hot],
                lock=label)],
            subject=label, score=p95,
            action=(f"split the state guarded by {label!r} (or "
                    "shorten its critical sections — a blocking "
                    "call held under it is a threadlint T003 "
                    "site); lockwatch's per-lock hold_p95_s says "
                    "whether holders or queuers dominate")))
    return out


_RULE_FNS: tuple = (_d001, _d002, _d003, _d004, _d005, _d006, _d007,
                    _d008, _d009, _d010, _d011, _d012, _d016)


# ---------------------------------------------------------------------------
# diagnosis + surfacing
# ---------------------------------------------------------------------------

def diagnose(view: TelemetryView) -> dict:
    """Run the full rule catalog over one view; returns the report
    with findings ranked most-severe first. A rule that throws is
    recorded in `errors` (never a lost diagnosis — the doctor's own
    failure mode must not be silence)."""
    findings: list = []
    errors: list = []
    for fn in _RULE_FNS:
        try:
            findings.extend(fn(view))
        except Exception as e:  # noqa: BLE001
            errors.append(f"{fn.__name__}: "
                          f"{type(e).__name__}: {e}"[:200])
    findings.sort(key=lambda f: (-_SEVERITY_RANK[f["severity"]],
                                 -f["score"], f["rule"]))
    report = {"schema": 1,
              "target": view.target,
              "platform": view.platform,
              "t": round(time.time(), 3),
              "healthy": not findings,
              "findings": findings,
              "rules_evaluated": sorted(LOCAL_RULES),
              "rules_fired": sorted({f["rule"] for f in findings}),
              "phases": phase_profile(view.spans)}
    if errors:
        report["errors"] = errors
    return report


def compact_finding(f: dict) -> dict:
    """The bounded projection of a finding that rides ledger records
    and /status.json (full evidence stays with the report). The
    structured `remedy` (D005's rebucket_hint — the scheduling input
    ROADMAP item 2 consumes) rides along, with long key lists
    truncated-and-counted rather than dropped."""
    out = {k: f.get(k) for k in
           ("rule", "name", "severity", "summary", "subject",
            "action", "score") if f.get(k) is not None}
    remedy = fleet.compact_hint(f.get("remedy"))
    if remedy is not None:
        out["remedy"] = remedy
    out["evidence"] = [
        {k: e.get(k) for k in ("series", "field", "indices", "values")
         if e.get(k) is not None}
        for e in (f.get("evidence") or [])[:4]]
    return out


def compact_report(report: dict) -> dict:
    """The `doctor` block /runs/<id>.json attaches."""
    return {"schema": 1, "target": report.get("target"),
            "healthy": bool(report.get("healthy")),
            "rules_fired": report.get("rules_fired") or [],
            "findings": [compact_finding(f) for f in
                         (report.get("findings") or [])
                         [:MAX_FINDINGS_LEDGER]]}


# in-process diagnosis history for /status.json (preflight.snapshot's
# sibling)
_LOCK = threading.Lock()
_RECENT: deque = deque(maxlen=32)
_CHECKED = 0
_LAST_REPORT: Optional[dict] = None


def record_report(report: dict, *, where: str,
                  ledger_name: Optional[str] = None) -> None:
    """Record one diagnosis into the observability planes it audits:
    a `doctor` metrics series point + counter per finding, a
    `kind="doctor"` ledger record (when `ledger_name` names the run),
    and the in-process recent window /status.json serves. Never
    raises — the diagnosis itself outranks its accounting."""
    global _CHECKED, _LAST_REPORT
    findings = report.get("findings") or []
    with _LOCK:
        _CHECKED += 1
        _LAST_REPORT = report
        for f in findings[:8]:
            _RECENT.append(compact_finding(f))
    try:
        from . import metrics as metrics_mod
        mx = metrics_mod.get_default()
        if mx.enabled:
            series = mx.series(
                "doctor", "diagnosis findings from the run doctor "
                          "(rule catalog D001-D016)")
            for f in findings:
                series.append({"rule": f["rule"],
                               "severity": f["severity"],
                               "target": str(report.get("target")),
                               "subject": f.get("subject"),
                               "summary": f["summary"],
                               "where": str(where)})
            mx.counter("doctor_runs_total",
                       "doctor diagnoses performed").inc(
                where=str(where))
            for f in findings:
                mx.counter("doctor_findings_total",
                           "doctor findings by rule").inc(
                    rule=f["rule"], severity=f["severity"])
    except Exception:  # noqa: BLE001
        pass
    if ledger_name:
        try:
            ledger_mod.record({
                "kind": "doctor", "name": str(ledger_name),
                "target": str(report.get("target")),
                "platform": report.get("platform"),
                "where": str(where),
                "healthy": bool(report.get("healthy")),
                "rules": report.get("rules_fired") or [],
                "findings_n": len(findings),
                "findings": [compact_finding(f) for f in
                             findings[:MAX_FINDINGS_LEDGER]],
                "phases": report.get("phases")})
        except Exception:  # noqa: BLE001
            pass


def snapshot() -> dict:
    """The `/status.json` `doctor` block: diagnoses run in this
    process, the severity mix of their findings, and a bounded
    recent-findings window."""
    with _LOCK:
        recent = list(_RECENT)[-8:]
        checked = _CHECKED
        last = _LAST_REPORT
    counts: dict = {}
    for f in recent:
        counts[f["severity"]] = counts.get(f["severity"], 0) + 1
    last_findings = (last.get("findings") or []) if last else []
    return {"checked": checked,
            "findings": counts,
            "healthy_last": (bool(last.get("healthy"))
                             if last else None),
            # the banner line /status renders: the LAST diagnosis's
            # top-ranked finding — None when it diagnosed healthy
            # (the recent window keeps history, but a stale finding
            # must never masquerade as the current verdict)
            "top": (compact_finding(last_findings[0])
                    if last_findings else None),
            "recent": recent}


def last_report() -> Optional[dict]:
    """The most recent in-process diagnosis (None before any)."""
    with _LOCK:
        return _LAST_REPORT


def _reset() -> None:
    """Clear the in-process diagnosis history (test isolation: the
    /doctor panel prefers the last in-process report, and one test's
    diagnosis must not become another's panel)."""
    global _CHECKED, _LAST_REPORT
    with _LOCK:
        _RECENT.clear()
        _CHECKED = 0
        _LAST_REPORT = None


def perfetto_instants(report: dict) -> list:
    """Instant-event annotations for trace.to_perfetto's `instants=`:
    one `{"t", "name"}` per evidence point that carries a wall stamp,
    so the offending rounds light up inside the span/counter view."""
    out: list = []
    for f in report.get("findings") or []:
        label = f"{f['rule']} {f['name']}"
        for ev in f.get("evidence") or []:
            for t in ev.get("t") or []:
                out.append({"t": float(t), "name": label})
                if len(out) >= 64:
                    return out
    return out


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def format_report(report: dict) -> str:
    """The human rendering of one report (the CLI's non-JSON path)."""
    lines = [f"doctor: target={report.get('target')} "
             f"platform={report.get('platform')} — "
             + ("HEALTHY (no findings)" if report.get("healthy") else
                f"{len(report.get('findings') or [])} finding(s), "
                f"rules {','.join(report.get('rules_fired') or [])}")]
    for f in report.get("findings") or []:
        subj = f" @ {f['subject']}" if f.get("subject") else ""
        lines.append(f"  [{f['severity']:8s}] {f['rule']} "
                     f"{f['name']}{subj}: {f['summary']}")
        if f.get("action"):
            lines.append(f"{'':14s}-> {f['action']}")
        for ev in (f.get("evidence") or [])[:2]:
            vals = ev.get("values")
            lines.append(f"{'':14s}evidence: {ev.get('series')}."
                         f"{ev.get('field')} idx={ev.get('indices')} "
                         f"values={vals}")
        if f.get("remedy"):
            lines.append(f"{'':14s}remedy: {f['remedy']}")
    ph = report.get("phases")
    if ph:
        lines.append(f"  phases: dominant {ph.get('dominant')!r} "
                     f"({ph.get('dominant_share'):.0%} of traced "
                     "wall)")
    for e in report.get("errors") or []:
        lines.append(f"  rule error: {e}")
    return "\n".join(lines)


def cli_main(options: dict, arguments: Optional[list] = None) -> int:
    """`python -m jepsen_tpu doctor <run_id|latest|bench>` — diagnose
    a recorded run (ledger id or "latest") or the bench round's
    artifacts ("bench"), print (or --json) the ranked findings, and
    bank the diagnosis in the doctor planes. `--watch` re-diagnoses
    whenever the store's ledger index changes (TTL-throttled by
    `--interval`, default 2s); watch passes are read-only — they
    never bank, so their own output cannot re-trigger them — and
    run-id targets share the `/runs/<id>.json` per-record diagnosis
    cache with the web panel (an unchanged record is a dict lookup,
    not a re-read)."""
    target = None
    for a in arguments or []:
        target = a
        break
    target = target or options.get("target") or "bench"
    root = options.get("root") or os.getcwd()
    store_root = options.get("store") or os.path.join(root, "store")
    if options.get("watch"):
        return _watch(dict(options, no_record=True), target, root,
                      store_root)
    return _cli_once(options, target, root, store_root)


def _cli_once(options: dict, target: str, root: str,
              store_root: str) -> int:
    try:
        if target == "bench":
            view = bench_view(root)
        else:
            view = run_view(store_root, target)
    except KeyError as e:
        print(f"doctor: {e.args[0]}")
        return 254
    report = diagnose(view)
    # bank the diagnosis in the STORE ledger it read from, so the
    # findings are queryable at /runs and the next round's D008 has a
    # phase baseline (--no-record for read-only inspection)
    with ledger_mod.use(ledger_mod.Ledger(store_root)):
        record_report(report, where="cli",
                      ledger_name=None if options.get("no_record")
                      else f"doctor-{target}")
    if options.get("json"):
        print(json.dumps(report, indent=2, default=str))
    else:
        print(format_report(report))
    if options.get("strict") and any(
            f["severity"] in ("critical", "warn")
            for f in report.get("findings") or []):
        return 1
    return 0


def _watch(options: dict, target: str, root: str,
           store_root: str) -> int:
    """The `doctor --watch` loop: poll the store ledger's index
    signature (Ledger.index_signature — the same (mtime_ns, size) key
    the web caches use) and re-diagnose only when it changed AND the
    TTL elapsed; a churning index costs one diagnosis per interval,
    an idle one costs a stat(2) per poll. Ctrl-C exits cleanly."""
    interval = max(0.5, float(options.get("interval") or 2.0))
    led = ledger_mod.Ledger(store_root)
    last_sig: object = ("never",)   # always diagnose the first pass
    last_t = 0.0
    try:
        while True:
            sig = led.index_signature()
            now = time.time()
            if sig != last_sig and (now - last_t) >= interval:
                last_sig, last_t = sig, now
                print(f"-- doctor watch {target} @ "
                      f"{time.strftime('%H:%M:%S')} --")
                if target not in ("bench", "latest") \
                        and not options.get("json"):
                    # an explicit run id rides the /runs/<id>.json
                    # per-record cache shared with the web panel
                    from . import web as web_mod
                    dc = web_mod.doctor_for_record(store_root,
                                                   target)
                    if dc is None:
                        print(f"doctor: no record {target!r} yet")
                    else:
                        print(f"healthy={dc.get('healthy')} "
                              f"rules_fired="
                              f"{dc.get('rules_fired')}")
                        for f in dc.get("findings") or []:
                            print(f"  [{f.get('severity')}] "
                                  f"{f.get('rule')} "
                                  f"{f.get('name')}: "
                                  f"{f.get('summary')}")
                else:
                    _cli_once(options, target, root, store_root)
            time.sleep(min(interval, 0.5))
    except KeyboardInterrupt:
        print("doctor: watch stopped")
        return 0
