"""Kernel occupancy observatory: per-round device counters + roofline.

ROADMAP item 5 (adaptive-W/K, compact-before-expand, >0.8 frontier
fill) needs per-round, per-lane visibility into what the WGL kernels
actually do — the whole-search averages the util blocks report hide
exactly the dynamics that matter (a search that runs full for 50
rounds and empty for 500 averages to the same fill as one that idles
uniformly). This module is the host half of that plane:

  * **drain** — the `wgl32`/`wgln` kernels write one `(RING_COLS,)`
    int32 row per round into an on-device ring (`wgl32.RING_ROWS`)
    that rides the packed poll summary, so per-round counters reach
    the host at existing poll boundaries with ZERO extra
    host<->device transfers and ZERO kernel changes between
    instrumented and uninstrumented runs (the CompileGuard proof in
    tests/test_occupancy.py). `drain_chunk` turns one summary into
    per-round dicts (`wgl_rounds` series points).
  * **fill / rate math** — `memo_hit_rate` is the ONE place the
    hits/(hits+inserts) ratio is computed (ops/wgl.py uses it for
    both the per-chunk points and the final util block, so the two
    can't drift); `build_block` folds drained rounds into the
    per-search `occupancy` result block.
  * **roofline attribution** — `roofline` classifies the search
    compute- vs memory-bound and reports achieved-vs-peak, reusing
    `ops.aot.peak_bf16_flops` for the chip peak and (when available)
    the compiler's own `cost_analysis()` via `cost_for`, which goes
    through `jax.stages.Lowered.cost_analysis` — tracing + lowering
    only, NO backend compile, so a CompileGuard zero-compile budget
    stays intact.
  * **Perfetto counter tracks** — `perfetto_counter_tracks` turns
    the registry's occupancy series into `trace_event` "C" counter
    tracks so fill/frontier/backlog render as graphs under the phase
    spans in ui.perfetto.dev.

Schemas are documented in doc/OBSERVABILITY.md ("Occupancy &
roofline") and linted by scripts/telemetry_lint.py.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .ops.wgl32 import RING_COLS, RING_ROWS, SUMMARY_HEAD

# Cap on per-round rows copied into a RESULT's occupancy block — the
# registry series keeps everything the ring surfaced; the result copy
# is for plots/reports and must not balloon a 100k-round search's
# result dict. Overflow is counted in `rounds_truncated`, never silent.
MAX_RESULT_ROUNDS = 2048

# ROADMAP item 5's tracked target: mean frontier fill per config.
TARGET_FILL = 0.8


def memo_hit_rate(hits, inserts) -> float:
    """hits / (hits + inserts), guarded — the single definition both
    the per-chunk telemetry points and the final util block use."""
    hits, inserts = int(hits), int(inserts)
    return round(hits / max(hits + inserts, 1), 4)


def drain_chunk(summary, rounds_before: int, K: int) -> tuple[list, int]:
    """Per-round occupancy rows from ONE packed poll summary.

    `summary` is the (SUMMARY_HEAD + RING_ROWS*RING_COLS,) int32 poll
    vector (already on the host — the drain adds no transfer);
    `rounds_before` is the cumulative rounds_total at the PREVIOUS
    poll, which anchors the first row's round span; `K` is the beam
    capacity fill is normalized by.

    Returns (rows, rounds_dropped): `rows` are dicts with round id,
    frontier (configs expanded), fill (frontier / (span * K) — span
    covers the depth-fused accel rounds, where one ring row spans
    `depth` levels), memo hits/inserts, survivors, post-compaction
    frontier, backlog and max linearized base; `rounds_dropped`
    counts rounds past RING_ROWS in this chunk (dropped on device,
    reported so coverage gaps are visible, never silent)."""
    s = np.asarray(summary).reshape(-1)
    if s.shape[0] < SUMMARY_HEAD + RING_COLS:
        return [], 0  # a ring-less summary (e.g. the legacy kernel)
    ring = s[SUMMARY_HEAD:SUMMARY_HEAD + RING_ROWS * RING_COLS]
    ring = ring.reshape(RING_ROWS, RING_COLS)
    writes = int(s[5])           # stats[1]: round-body calls this chunk
    rounds_total = int(s[9])     # stats[5]: cumulative rounds
    rows: list = []
    prev = int(rounds_before)
    for r in ring[:min(writes, RING_ROWS)]:
        rnd = int(r[0])
        span = max(1, rnd - prev)
        prev = rnd
        frontier = int(r[1])
        rows.append({
            "round": rnd,
            "span": span,
            "frontier": frontier,
            "fill": round(frontier / max(span * K, 1), 4),
            "memo_hits": int(r[2]),
            # memo inserts == compaction survivors by construction
            # (a successor survives iff its signature inserted), so
            # ONE field carries both meanings
            "memo_inserts": int(r[3]),
            "frontier_after": int(r[4]),
            "backlog": int(r[5]),
            "max_base": int(r[6]),
        })
    covered = (rows[-1]["round"] - int(rounds_before)) if rows else 0
    dropped = max(0, (rounds_total - int(rounds_before)) - covered)
    return rows, dropped


def _fill_stats(rounds: Sequence[dict]) -> dict:
    fills = [r["fill"] for r in rounds if r.get("fill") is not None]
    if not fills:
        return {"mean": None, "min": None, "max": None, "last": None}
    return {"mean": round(float(np.mean(fills)), 4),
            "min": round(float(np.min(fills)), 4),
            "max": round(float(np.max(fills)), 4),
            "last": fills[-1]}


# Compiler cost analysis per kernel shape bucket, computed at most
# once per process per key. `None` (analysis unavailable) is cached
# too — a failing lowering must not be retried per search.
_COST_CACHE: dict = {}


def cost_for(key: tuple, lower_fn) -> Optional[dict]:
    """{'flops', 'bytes_accessed'} per chunk call from the compiler's
    own cost analysis, via `lower_fn() -> jax.stages.Lowered`.
    Lowering traces the kernel but performs NO backend compile (no
    `/jax/core/compile/backend_compile_duration` event), so calling
    this under a CompileGuard zero-compile budget is safe — asserted
    by tests/test_occupancy.py. Cached per shape-bucket `key`.

    NB (same caveat as ops/aot.py): HloCostAnalysis counts a
    while-loop body ONCE and charges gathers at full-operand width,
    so these are per-ROUND numbers and an upper bound on traffic."""
    if key in _COST_CACHE:
        return _COST_CACHE[key]
    return _cost_fill(key, lower_fn)


def cost_cached(key: tuple) -> Optional[dict]:
    """The cached per-round cost for `key`, or None when the kernel
    was never lowered in this process — lets a probe-only preflight
    plan reuse the executed check's numbers without re-encoding."""
    return _COST_CACHE.get(key)


def per_shard_cost(cost: Optional[dict], n_shards: int
                   ) -> Optional[dict]:
    """A whole-kernel per-round cost scaled to ONE shard of the
    mesh-sharded Elle closure's word-column layout: flops split
    evenly (each shard squares its own column block), bytes scaled by
    (1 + 2/n_shards)/3 — the gathered full row set is read once per
    shard regardless of the split, while the two writable blocks
    (local r + local accumulator) shrink with it. Used by
    elle/tpu._squaring_select to sanity-check the analytic per-shard
    HBM bill against the compiler's own packed-closure numbers."""
    if not cost or n_shards < 1:
        return None
    ns = int(n_shards)
    return {"flops": cost.get("flops", 0.0) / ns,
            "bytes_accessed": cost.get("bytes_accessed", 0.0)
            * (1.0 + 2.0 / ns) / 3.0,
            "n_shards": ns}


def _cost_fill(key: tuple, lower_fn) -> Optional[dict]:
    out: Optional[dict] = None
    try:
        ca = lower_fn().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            out = {"flops": float(ca.get("flops", 0.0)),
                   "bytes_accessed": float(
                       ca.get("bytes accessed", 0.0))}
    except Exception:  # noqa: BLE001 — cost analysis is best-effort;
        out = None     # the analytic model below covers its absence
    _COST_CACHE[key] = out
    return out


def roofline(*, K: int, row_cols: int, probes: int, rounds: int,
             wall_s: float, device_kind: Optional[str] = None,
             cost: Optional[dict] = None) -> dict:
    """Classify the search compute- vs memory-bound and report
    achieved-vs-peak, per round.

    The peak comes from `ops.aot.peak_bf16_flops` for the detected
    chip (v5e spec default, labeled, when unknown — e.g. on the cpu
    tier-1 runs); HBM peak is the v5e spec number the AOT roofline
    uses. Per-round flops/bytes come from the compiler's cost
    analysis when `cost` is provided, else from the analytic memo-
    stream model (K * row_cols successor rows x probes x 16 B — the
    same currency ops/aot._wgl_analytic and the util block report).
    `achieved_frac` = roofline-bound time / measured round time: how
    close the measured rounds run to the modeled bound (latency-bound
    rounds sit far below 1.0 — that gap IS the finding, see the
    model_status note in ops/aot.py)."""
    from .ops import aot as aot_mod

    peak_flops, chip = aot_mod.peak_bf16_flops(device_kind)
    peak_bytes = aot_mod.V5E_PEAK_HBM_BYTES
    est_bytes = float(K * row_cols * probes * 16)
    if cost:
        flops = float(cost.get("flops") or 0.0)
        byts = float(cost.get("bytes_accessed") or 0.0) or est_bytes
        source = "compiler-cost-analysis"
    else:
        # the search is gather/hash-bound; a handful of int ops per
        # successor word is a generous flop model
        flops = float(K * row_cols * 64)
        byts = est_bytes
        source = "analytic"
    t_comp = flops / peak_flops
    t_mem = byts / peak_bytes
    t_bound = max(t_comp, t_mem, 1e-12)
    round_time = wall_s / max(rounds, 1)
    return {
        "source": source,
        "bound": "compute" if t_comp >= t_mem else "memory",
        "flops_per_round": flops,
        "bytes_per_round": byts,
        "arithmetic_intensity": round(flops / max(byts, 1.0), 6),
        "peak_bf16_flops": peak_flops,
        "peak_hbm_bytes_per_s": peak_bytes,
        "peak_chip": chip,
        "roofline_round_time_s": t_bound,
        "measured_round_time_s": round(round_time, 9),
        "achieved_frac": round(min(1.0, t_bound / max(round_time,
                                                      1e-12)), 6),
    }


def build_block(rounds: Sequence[dict], *, K: int, row_cols: int,
                probes: int, kernel: str, platform: str,
                wall_s: float, rounds_total: int,
                configs_explored: int, memo_hits: int,
                memo_inserts: int, rounds_dropped: int = 0,
                rounds_seen: Optional[int] = None,
                device_kind: Optional[str] = None,
                cost: Optional[dict] = None) -> dict:
    """The per-search `occupancy` result block (doc/OBSERVABILITY.md):
    drained per-round rows (capped at MAX_RESULT_ROUNDS, overflow
    counted in `rounds_truncated` — `rounds_seen` is what the drain
    surfaced in total, when the caller capped before passing), fill
    statistics, memo dedup, expansion totals, and the roofline
    attribution. Every count is device-measured; only the byte/flop
    models are estimates (labeled by `roofline.source`)."""
    rounds = list(rounds)
    kept = rounds[:MAX_RESULT_ROUNDS]
    seen = len(rounds) if rounds_seen is None else int(rounds_seen)
    # compaction survivors == memo inserts (see drain_chunk)
    survivors = sum(r.get("memo_inserts", 0) for r in rounds)
    return {
        "schema": 1,
        "kernel": kernel,
        "platform": platform,
        "K": K,
        "rounds_total": int(rounds_total),
        "rounds_seen": seen,
        "rounds_dropped": int(rounds_dropped),
        "rounds_truncated": max(0, seen - len(kept)),
        "fill": _fill_stats(rounds),
        "memo": {"hits": int(memo_hits), "inserts": int(memo_inserts),
                 "hit_rate": memo_hit_rate(memo_hits, memo_inserts)},
        "expansion": {
            "configs_explored": int(configs_explored),
            "survivors_seen": int(survivors),
            "expanded_per_round": round(
                configs_explored / max(rounds_total, 1), 2)},
        "roofline": roofline(K=K, row_cols=row_cols, probes=probes,
                             rounds=rounds_total, wall_s=wall_s,
                             device_kind=device_kind, cost=cost),
        "rounds": kept,
    }


def safe_device_kind() -> Optional[str]:
    """The jax device kind for roofline peak lookup, or None when the
    backend is unavailable/wedged (peak then falls back to the
    labeled v5e default — never a hang on this hot path: callers are
    mid-search, so the backend is already initialized)."""
    try:
        import jax
        return jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001
        return None


def heatmap_points(rounds: Sequence[dict], lane: int = 0) -> list:
    """`{round, lane, fill}` triples for plots.occupancy_heatmap —
    the single-search view is a 1-lane strip; the batched fan-out
    emits one lane per key (parallel/batched.py)."""
    return [{"round": int(r["round"]), "lane": int(lane),
             "fill": float(r.get("fill") or 0.0)}
            for r in rounds if r.get("round") is not None]


def perfetto_counter_tracks(registry) -> dict:
    """Counter tracks for trace.to_perfetto's `counters=` input, from
    the occupancy/telemetry series a run recorded:

      wgl fill        — per-round frontier fill (wgl_rounds)
      wgl frontier/backlog — per-poll beam + backlog (wgl_chunks)
      batched live_keys    — live lanes per poll (wgl_batched_chunks)
      mesh sched actions   — cumulative scheduler actions of the
                             mesh fan-out (`mesh_sched` series,
                             parallel/mesh.py): each steal/rebucket
                             steps the counter, so scheduling bursts
                             line up with the fill lanes above
      hbm bytes <device>   — bytes_in_use per device id (`hbm`
                             series, devices.py) — one counter lane
                             per device, so a mesh run's memory
                             trajectory renders per chip
      elle gather bytes    — the sharded Elle closure's per-iteration
                             all_gather volume (`elle_closure` series
                             points with kernel == "sharded"): spikes
                             here against the hbm lanes above show
                             whether a 100k closure is collective- or
                             bandwidth-bound

    Points ride their metrics `t` wall-clock stamps, so the counter
    graphs line up with the phase spans in ui.perfetto.dev."""
    tracks: dict = {}

    def add(series: str, field: str, track: str) -> None:
        pts = registry.series(series).points
        vals = [(p["t"], p[field]) for p in pts
                if p.get("t") is not None
                and isinstance(p.get(field), (int, float))]
        if vals:
            tracks[track] = vals

    try:
        add("wgl_rounds", "fill", "wgl fill")
        add("wgl_chunks", "frontier", "wgl frontier")
        add("wgl_chunks", "backlog", "wgl backlog")
        add("wgl_batched_chunks", "live_keys", "batched live keys")
        add("elle_closure", "gather_bytes", "elle gather bytes")
        n_sched = 0
        sched_vals = []
        for p in registry.series("mesh_sched").points:
            if p.get("t") is not None:
                n_sched += 1
                sched_vals.append((p["t"], n_sched))
        if sched_vals:
            tracks["mesh sched actions"] = sched_vals
        by_dev: dict = {}
        for p in registry.series("hbm").points:
            if p.get("t") is not None and isinstance(
                    p.get("bytes_in_use"), (int, float)):
                by_dev.setdefault(str(p.get("device")), []).append(
                    (p["t"], p["bytes_in_use"]))
        for dev, vals in sorted(by_dev.items()):
            tracks[f"hbm bytes {dev}"] = vals
    except Exception:  # noqa: BLE001 — a torn registry never blocks
        pass           # the trace export itself
    return tracks
