"""Helpers for poking at stored test runs from a Python shell
(jepsen/src/jepsen/repl.clj:6-9).

    >>> from jepsen_tpu import repl
    >>> t = repl.latest_test()
    >>> t["results"]["valid?"]
"""

from __future__ import annotations

from typing import Optional

from . import store


def latest_test(store_root: str = store.BASE_DIR) -> Optional[dict]:
    """The most recently run test, loaded lazily from the store
    (repl.clj:6-9)."""
    return store.load_latest(store_root)
