"""Database lifecycle protocols (parity with jepsen.db,
`jepsen/src/jepsen/db.clj`): `DB` setup/teardown (db.clj:11-13), optional
`Process` start/kill (:18-24), `Pause` (:26-29), `Primary` (:31-38),
`LogFiles` (:40-41), a tcpdump capture DB (:49-115), and `cycle`
(teardown -> setup on all nodes with 3 retries on SetupFailed,
:117-158)."""

from __future__ import annotations

import logging
import time as _time
from typing import Optional, Sequence

from . import control as c
from .control import nodeutil as cu

log = logging.getLogger("jepsen_tpu.db")


class DB:
    def setup(self, test: dict, node: str) -> None:
        return None

    def teardown(self, test: dict, node: str) -> None:
        return None


class Process:
    """Optional: starting and killing the DB's processes (db.clj:18-24)."""

    def start(self, test: dict, node: str):
        raise NotImplementedError

    def kill(self, test: dict, node: str):
        raise NotImplementedError


class Pause:
    """Optional: pausing/resuming processes (db.clj:26-29)."""

    def pause(self, test: dict, node: str):
        raise NotImplementedError

    def resume(self, test: dict, node: str):
        raise NotImplementedError


class Primary:
    """Optional: databases with a notion of primaries (db.clj:31-38)."""

    def primaries(self, test: dict) -> Sequence[str]:
        raise NotImplementedError

    def setup_primary(self, test: dict, node: str) -> None:
        return None


class LogFiles:
    def log_files(self, test: dict, node: str) -> Sequence[str]:
        return []


class Noop(DB):
    """Does nothing (db.clj:43-47)."""


noop = Noop


class SetupFailed(Exception):
    """Throw from DB.setup to request a teardown+retry (db.clj:117-120)."""


class Tcpdump(DB, LogFiles):
    """Captures packets from setup to teardown (db.clj:49-115). Options:
    ports (list), clients_only (bool), filter (str)."""

    DIR = "/tmp/jepsen/tcpdump"

    def __init__(self, opts: Optional[dict] = None):
        self.opts = opts or {}

    @property
    def log_file(self):
        return f"{self.DIR}/log"

    @property
    def cap_file(self):
        return f"{self.DIR}/tcpdump"

    @property
    def pid_file(self):
        return f"{self.DIR}/pid"

    def setup(self, test, node):
        with c.su():
            c.exec_("mkdir", "-p", self.DIR)
            filters = []
            if self.opts.get("ports"):
                filters.append(" and ".join(
                    f"port {p}" for p in self.opts["ports"]))
            if self.opts.get("clients_only"):
                from .control import netinfo
                filters.append(f"host {netinfo.control_ip()}")
            if self.opts.get("filter"):
                filters.append(self.opts["filter"])
            cu.start_daemon(
                {"logfile": self.log_file, "pidfile": self.pid_file,
                 "chdir": self.DIR},
                "/usr/sbin/tcpdump",
                "-w", self.cap_file, "-s", "65535", "-B", "16384", "-U",
                " and ".join(filters))

    def teardown(self, test, node):
        with c.su():
            pid = cu.meh(c.exec_, "cat", self.pid_file)
            if pid:
                cu.meh(c.exec_, "kill", "-s", "INT", pid.strip())
                for _ in range(100):
                    if cu.meh(c.exec_, "ps", "-p", pid.strip()) is None:
                        break
                    _time.sleep(0.05)
            cu.stop_daemon("tcpdump", self.pid_file)
            c.exec_("rm", "-rf", self.DIR)

    def log_files(self, test, node):
        return [self.log_file, self.cap_file]


def tcpdump(opts: Optional[dict] = None) -> Tcpdump:
    return Tcpdump(opts)


CYCLE_TRIES = 3  # db.clj:117-120


def cycle(test: dict) -> None:
    """Tear down then set up the DB on all nodes concurrently, retrying
    the whole cycle up to CYCLE_TRIES times on SetupFailed
    (db.clj:122-158)."""
    db = test["db"]
    tries = CYCLE_TRIES
    while True:
        log.info("Tearing down DB")
        c.on_nodes(test, db.teardown)
        try:
            log.info("Setting up DB")
            c.on_nodes(test, db.setup)
            if isinstance(db, Primary):
                primary = test["nodes"][0]
                log.info("Setting up primary %s", primary)
                c.on_nodes(test, lambda t, n: db.setup_primary(t, n),
                           [primary])
            return
        except SetupFailed:
            tries -= 1
            if tries < 1:
                raise
            log.warning("Unable to set up database; retrying...")
