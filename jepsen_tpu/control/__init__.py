"""Remote-control DSL: run shell commands on cluster nodes.

Capability parity with jepsen.control (`jepsen/src/jepsen/control.clj`):
scoped dynamic state binds the current host/session/dir/sudo
(control.clj:40-53 uses Clojure dynamic vars; here a threading.local so
`on_nodes`'s thread-per-node fan-out gets independent bindings), with
`exec` (escaped commands -> stdout, control.clj:138-157), `upload` /
`download`, `cd`/`sudo_user`/`su` scopes (control.clj:203-218), `on` /
`on_many` / `on_nodes` parallel fan-out (control.clj:272-311), and
`with_ssh`/`with_remote` configuration scopes (control.clj:226-262).

The default remote is the OpenSSH subprocess transport wrapped in
retries; `{"dummy?": True}` in the test's ssh map swaps in the no-op
remote exactly as the reference's `:dummy?` flag does (control.clj:40).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Callable, Optional, Sequence

from ..util import real_pmap
from . import dummy as dummy_remote_mod
from . import retry as retry_mod
from . import sshcli
from .core import (AND, PIPE, Literal, NonzeroExit, Remote, env, escape, lit,
                   throw_on_nonzero_exit)

__all__ = ["escape", "lit", "env", "Literal", "NonzeroExit", "Remote",
           "exec_", "exec_star", "upload", "download", "cd", "sudo_user",
           "su", "trace", "on", "on_many", "on_nodes", "with_ssh",
           "with_remote", "with_session", "session", "disconnect",
           "AND", "PIPE", "state"]


class _State(threading.local):
    """Per-thread bindings (control.clj:40-53)."""

    def __init__(self):
        self.dummy = False
        self.host = None
        self.session = None
        self.trace = False
        self.dir = "/"
        self.sudo = None
        self.sudo_password = None
        self.username = "root"
        self.password = "root"
        self.port = 22
        self.private_key_path = None
        self.strict_host_key_checking = "yes"
        self.remote = None  # default constructed lazily


state = _State()


def default_remote() -> Remote:
    return retry_mod.remote(sshcli.remote())


def conn_spec() -> dict:
    return {"dummy": state.dummy,
            "host": state.host,
            "port": state.port,
            "username": state.username,
            "password": state.password,
            "private_key_path": state.private_key_path,
            "strict_host_key_checking": state.strict_host_key_checking}


def cmd_context() -> dict:
    return {"dir": state.dir,
            "sudo": state.sudo,
            "sudo_password": state.sudo_password}


_STATE_FIELDS = ("dummy", "host", "session", "trace", "dir", "sudo",
                 "sudo_password", "username", "password", "port",
                 "private_key_path", "strict_host_key_checking", "remote")


@contextmanager
def _bind(**kw):
    old = {k: getattr(state, k) for k in kw}
    for k, v in kw.items():
        setattr(state, k, v)
    try:
        yield
    finally:
        for k, v in old.items():
            setattr(state, k, v)


def _snapshot() -> dict:
    """Capture this thread's bindings so fan-out threads inherit them
    (the reference's bound-fn in on-nodes, control.clj:303-309)."""
    return {k: getattr(state, k) for k in _STATE_FIELDS}


def bound_fn(f: Callable) -> Callable:
    """Wrap f so it runs under the calling thread's control bindings —
    the reference's bound-fn* (used e.g. to open sessions from worker
    threads, core.clj:285-287)."""
    snap = _snapshot()

    def wrapped(*args, **kw):
        with _bind(**snap):
            return f(*args, **kw)

    return wrapped


def expand_path(path: str) -> str:
    if path.startswith("/"):
        return path
    d = state.dir or "/"
    return d + ("" if d.endswith("/") else "/") + path


@contextmanager
def cd(dir: str):
    """Evaluate body in the given directory (control.clj:203-207)."""
    with _bind(dir=expand_path(dir)):
        yield


@contextmanager
def sudo_user(user: str):
    with _bind(sudo=user):
        yield


@contextmanager
def su():
    """sudo root (control.clj:215-218)."""
    with _bind(sudo="root"):
        yield


@contextmanager
def trace():
    with _bind(trace=True):
        yield


def wrap_cd(action: dict) -> dict:
    if state.dir:
        return {**action, "cmd": f"cd {escape(state.dir)}; " + action["cmd"]}
    return action


class NoSessionError(Exception):
    pass


def ssh_star(action: dict) -> dict:
    """Evaluate an action against the current host (control.clj:125-136)."""
    if state.session is None:
        raise NoSessionError(
            "Unable to perform a control action: no session bound for "
            "this thread. Use on()/on_nodes()/with_session().")
    import logging
    if state.trace:
        logging.getLogger("jepsen_tpu.control").info(
            "Host: %s action: %r", state.host, action)
    return {**state.session.execute(cmd_context(), action),
            "host": state.host, "action": action}


def just_stdout(result: dict) -> str:
    return result.get("out", "").rstrip("\n")


def exec_star(*commands) -> str:
    """Like exec_, without escaping (control.clj:138-148)."""
    cmd = " ".join(str(c) for c in commands)
    action = wrap_cd({"cmd": cmd})
    # sudo wrapping happens in the Remote (core.wrap_sudo) from context
    return just_stdout(throw_on_nonzero_exit(ssh_star(action)))


def exec_(*commands) -> str:
    """Run a shell command (all args escaped); return stdout, raising on
    nonzero exit (control.clj:150-157)."""
    return exec_star(*(escape(c) for c in commands))


def upload(local_paths, remote_path) -> str:
    """Copy local path(s) to the remote node (control.clj:167-178)."""
    if state.session is None:
        raise NoSessionError("no session bound")
    state.session.upload(cmd_context(), local_paths, remote_path, {})
    return remote_path


def upload_text(text: str, remote_path: str) -> str:
    """Upload a string's contents to a remote path (the reference's
    upload-resource!, control.clj:175-185, generalized)."""
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".upload") as f:
        f.write(text)
        f.flush()
        upload(f.name, remote_path)
    return remote_path


def download(remote_paths, local_path) -> None:
    """Copy remote path(s) here (control.clj:186-189)."""
    if state.session is None:
        raise NoSessionError("no session bound")
    state.session.download(cmd_context(), remote_paths, local_path, {})


def session(host: str) -> Remote:
    """A connected Remote for the given host (control.clj:225-229)."""
    base = state.remote
    if base is None:
        base = dummy_remote_mod.remote() if state.dummy else default_remote()
    return base.connect({**conn_spec(), "host": host})


def disconnect(sess: Remote) -> None:
    sess.disconnect()


@contextmanager
def with_remote(remote: Remote):
    with _bind(remote=remote):
        yield


def named_remote(name: str) -> Remote:
    """A Remote by name: "cli" (OpenSSH binary, the default stack) or
    "native" (the from-scratch SSH-2 implementation, sshnative.py) —
    the reference's clj-ssh/sshj duality, selected via the ssh map's
    "remote" key the way its :remote option picks a stack."""
    if name == "native":
        from . import sshnative
        return retry_mod.remote(sshnative.remote())
    if name in ("cli", "ssh"):
        return default_remote()
    raise ValueError(f"unknown remote {name!r} (want cli or native)")


@contextmanager
def with_ssh(ssh: Optional[dict]):
    """Bind SSH configuration from a test's ssh map (control.clj:241-262).
    ssh["remote"] ("cli" | "native") selects the transport stack."""
    ssh = ssh or {}
    if ssh.get("remote") and state.remote is None:
        with _bind(remote=named_remote(ssh["remote"])):
            with with_ssh({k: v for k, v in ssh.items()
                           if k != "remote"}):
                yield
            return
    with _bind(dummy=ssh.get("dummy?", state.dummy),
               username=ssh.get("username", state.username),
               password=ssh.get("password", state.password),
               sudo_password=ssh.get("sudo-password", state.sudo_password),
               port=ssh.get("port", state.port),
               private_key_path=ssh.get("private-key-path",
                                        state.private_key_path),
               strict_host_key_checking=ssh.get("strict-host-key-checking",
                                                state.strict_host_key_checking)):
        yield


@contextmanager
def with_session(host: str, sess: Remote):
    """Bind host + session without opening/closing (control.clj:264-270)."""
    with _bind(host=host, session=sess):
        yield


@contextmanager
def on(host: str):
    """Open a session to host, evaluate body, close (control.clj:272-281)."""
    sess = session(host)
    try:
        with with_session(host, sess):
            yield
    finally:
        disconnect(sess)


def on_many(hosts: Sequence[str], f: Callable[[], Any]) -> dict:
    """Run f() on each host in parallel with its session bound; returns
    {host: result} (control.clj:283-293)."""
    snap = _snapshot()

    def run(host):
        with _bind(**snap), on(host):
            return f()
    return dict(zip(hosts, real_pmap(run, hosts)))


def on_nodes(test: dict, f: Callable[[dict, str], Any],
             nodes: Optional[Sequence[str]] = None) -> dict:
    """Evaluate (f test node) in parallel on each node, with that node's
    session from test["sessions"] bound (control.clj:295-311)."""
    if nodes is None:
        nodes = test["nodes"]
    sessions = test.get("sessions") or {}
    snap = _snapshot()

    def run(node):
        sess = sessions.get(node)
        assert sess is not None, f"no session for node {node!r}"
        with _bind(**snap), with_session(node, sess):
            return f(test, node)

    return dict(zip(nodes, real_pmap(run, nodes)))
