"""Remote over `kubectl exec` / `kubectl cp` — for k8s pods (parity with
jepsen.control.k8s, `control/k8s.clj:1-111`). Node names are pod names;
an optional namespace comes from the conn spec."""

from __future__ import annotations

import os
import subprocess
from typing import Optional

from .core import Remote, wrap_sudo


class K8sRemote(Remote):
    def __init__(self, pod: Optional[str] = None,
                 namespace: Optional[str] = None):
        self.pod = pod
        self.namespace = namespace

    def connect(self, conn_spec):
        return K8sRemote(conn_spec["host"],
                         conn_spec.get("namespace") or self.namespace)

    def _ns(self) -> list:
        return ["-n", self.namespace] if self.namespace else []

    def execute(self, context, action):
        action = wrap_sudo(context, action)
        res = subprocess.run(
            ["kubectl", "exec", "-i", *self._ns(), self.pod, "--",
             "bash", "-c", action["cmd"]],
            input=(action.get("in") or "").encode() if action.get("in")
            else None,
            capture_output=True, timeout=action.get("timeout"))
        return {**action, "exit": res.returncode,
                "out": res.stdout.decode(errors="replace"),
                "err": res.stderr.decode(errors="replace"),
                "action": action}

    def upload(self, context, local_paths, remote_path, opts=None):
        if isinstance(local_paths, (str, os.PathLike)):
            local_paths = [local_paths]
        for p in local_paths:
            subprocess.run(["kubectl", "cp", *self._ns(), str(p),
                            f"{self.pod}:{remote_path}"], check=True)

    def download(self, context, remote_paths, local_path, opts=None):
        if isinstance(remote_paths, (str, os.PathLike)):
            remote_paths = [remote_paths]
        for p in remote_paths:
            subprocess.run(["kubectl", "cp", *self._ns(),
                            f"{self.pod}:{p}", str(local_path)], check=True)


def remote() -> K8sRemote:
    return K8sRemote()
