"""Remote-execution protocol + shell command algebra.

Capability parity with jepsen.control.core
(`jepsen/src/jepsen/control/core.clj`): the `Remote` protocol
(connect/disconnect/execute/upload/download, core.clj:7-58), shell
escaping with `Literal` passthrough (core.clj:62-110), env-var
construction (core.clj:112-140), sudo wrapping (core.clj:142-153), and
nonzero-exit enforcement (core.clj:155-177).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence


@dataclass(frozen=True)
class Literal:
    """A string passed unescaped to the shell (core.clj:60-65)."""

    string: str


def lit(s: str) -> Literal:
    return Literal(s)


PIPE = lit("|")
AND = lit("&&")

_NEEDS_QUOTING = re.compile(r'[\\$`"\s(){}\[\]*?<>&;]')
_QUOTE_CHARS = re.compile(r'([\\$`"])')


def escape(s) -> str:
    """Escape a thing for the shell (core.clj:67-110): None -> empty,
    Literals pass through, lists/sets/tuples escape elementwise and join
    with spaces, strings quote when they contain metacharacters."""
    if s is None:
        return ""
    if isinstance(s, Literal):
        return s.string
    if isinstance(s, (list, tuple, set, frozenset)):
        items = sorted(s, key=str) if isinstance(s, (set, frozenset)) else s
        return " ".join(escape(x) for x in items)
    s = str(s)
    if s == "":
        return '""'
    if _NEEDS_QUOTING.search(s):
        return '"' + _QUOTE_CHARS.sub(r"\\\1", s) + '"'
    return s


def env(e) -> Optional[Literal]:
    """Build an env-var prefix string from a dict (core.clj:112-140)."""
    if e is None:
        return None
    if isinstance(e, Literal):
        return e
    if isinstance(e, str):
        return lit(e)
    if isinstance(e, dict):
        return lit(" ".join(f"{k}={escape(v)}" for k, v in e.items()))
    raise TypeError(f"can't build env from {e!r}")


def wrap_sudo(context: dict, action: dict) -> dict:
    """Wrap an action's :cmd in sudo, per the context's sudo/sudo_password
    (core.clj:142-153)."""
    sudo = context.get("sudo")
    if not sudo:
        return action
    out = dict(action)
    out["cmd"] = f"sudo -k -S -u {sudo} bash -c " + escape(action["cmd"])
    pw = context.get("sudo_password")
    if pw:
        out["in"] = pw + "\n" + (action.get("in") or "")
    return out


class NonzeroExit(Exception):
    """A remote command exited with nonzero status (core.clj:155-177)."""

    def __init__(self, result: dict):
        self.result = result
        action = result.get("action") or {}
        super().__init__(
            f"Command exited with non-zero status {result.get('exit')} on "
            f"node {result.get('host')}:\n{action.get('cmd')}\n\n"
            f"STDIN:\n{action.get('in')}\n\nSTDOUT:\n{result.get('out')}\n\n"
            f"STDERR:\n{result.get('err')}")


def throw_on_nonzero_exit(result: dict) -> dict:
    if result.get("exit") != 0:
        raise NonzeroExit(result)
    return result


class Remote:
    """Base remote (core.clj:7-58). Context maps carry dir/sudo/
    sudo_password; conn specs carry host/port/username/password/
    private_key_path/strict_host_key_checking."""

    def connect(self, conn_spec: dict) -> "Remote":
        raise NotImplementedError

    def disconnect(self) -> None:
        return None

    def execute(self, context: dict, action: dict) -> dict:
        """Run action {"cmd": ..., "in": ...}; return it with exit/out/err."""
        raise NotImplementedError

    def upload(self, context: dict, local_paths, remote_path,
               opts: Optional[dict] = None) -> None:
        raise NotImplementedError

    def download(self, context: dict, remote_paths, local_path,
                 opts: Optional[dict] = None) -> None:
        raise NotImplementedError
