"""NativeSSHRemote — the second, independent SSH transport.

Implements the `Remote` protocol (control/core.py) directly over the
from-scratch SSH-2 engine (control/sshwire.py): no ssh binary, no ssh
library. Selectable via ``ssh={"remote": "native", ...}`` or by
constructing it explicitly; shares the retry/reconnect wrappers like
every other remote (the reference's second stack, sshj, plugs into
jepsen the same way — control/sshj.clj:107-181).

One TCP connection per Remote; each execute/upload/download opens a
fresh session channel on it (SSH multiplexing, RFC 4254). Uploads and
downloads ride exec'd `cat` — capability-equivalent to the scp
subsystem with far less protocol surface, and the reference itself
falls back to plain-exec tactics when scp misbehaves.
"""

from __future__ import annotations

import socket
import struct
from typing import Optional

from . import sshwire as w
from .core import Remote

DEFAULT_PORT = 22


class NativeSSHRemote(Remote):
    def __init__(self, conn_spec: Optional[dict] = None):
        self.spec = conn_spec or {}
        self.ep: Optional[w.SshEndpoint] = None
        self.host_key: Optional[bytes] = None
        self._chan_seq = 0

    # -- Remote protocol ----------------------------------------------------
    def connect(self, conn_spec: dict) -> "NativeSSHRemote":
        r = NativeSSHRemote(conn_spec)
        r._connect()
        return r

    def _connect(self):
        spec = self.spec
        host = spec.get("host") or spec.get("hostname")
        port = int(spec.get("port") or DEFAULT_PORT)
        timeout = float(spec.get("connect_timeout") or 10.0)
        sock = socket.create_connection((host, port), timeout=timeout)
        sock.settimeout(float(spec.get("timeout") or 30.0))
        ep = w.SshEndpoint(sock)
        try:
            pinned = spec.get("hostkey")  # raw 32B ed25519, optional
            self.host_key = w.client_handshake(ep, pinned)
            self._auth(ep)
        except Exception:
            ep.close()
            raise
        self.ep = ep

    def _auth(self, ep: w.SshEndpoint):
        user = (self.spec.get("username") or "root").encode()
        password = self.spec.get("password")
        ep.send_packet(bytes([w.MSG_SERVICE_REQUEST])
                       + w.put_string(b"ssh-userauth"))
        ep.recv_msg(w.MSG_SERVICE_ACCEPT)
        # probe with "none" (some rigs allow it); else password
        ep.send_packet(bytes([w.MSG_USERAUTH_REQUEST])
                       + w.put_string(user)
                       + w.put_string(b"ssh-connection")
                       + w.put_string(b"none"))
        t, _ = self._recv_auth(ep)
        if t == w.MSG_USERAUTH_SUCCESS:
            return
        if password is None:
            raise w.SshError("auth: none rejected and no password set")
        ep.send_packet(bytes([w.MSG_USERAUTH_REQUEST])
                       + w.put_string(user)
                       + w.put_string(b"ssh-connection")
                       + w.put_string(b"password") + b"\x00"
                       + w.put_string(password.encode()))
        t, _ = self._recv_auth(ep)
        if t != w.MSG_USERAUTH_SUCCESS:
            raise w.SshError("auth: password rejected")

    @staticmethod
    def _recv_auth(ep: w.SshEndpoint):
        while True:
            t, c = ep.recv_msg()
            if t == w.MSG_USERAUTH_BANNER:
                continue
            if t in (w.MSG_USERAUTH_SUCCESS, w.MSG_USERAUTH_FAILURE):
                return t, c
            raise w.SshError(f"unexpected auth message {t}")

    def disconnect(self) -> None:
        if self.ep is not None:
            try:
                self.ep.send_packet(
                    bytes([w.MSG_DISCONNECT])
                    + b"\x00\x00\x00\x0b"  # SSH_DISCONNECT_BY_APPLICATION
                    + w.put_string(b"bye") + w.put_string(b""))
            except OSError:
                pass
            self.ep.close()
            self.ep = None

    # -- session channels ---------------------------------------------------
    def _exec(self, cmd: str, stdin: bytes = b"",
              raw: bool = False) -> dict:
        """One exec channel: returns {"exit", "out", "err"}; with
        raw=True, "out" stays bytes (byte-faithful downloads)."""
        ep = self.ep
        if ep is None:
            raise w.SshError("not connected")
        my_id = self._chan_seq
        self._chan_seq += 1
        ep.send_packet(bytes([w.MSG_CHANNEL_OPEN])
                       + w.put_string(b"session")
                       + struct.pack(">III", my_id, 0x7FFFFFFF, 32768))
        t, c = ep.recv_msg(w.MSG_CHANNEL_OPEN_CONFIRMATION,
                           w.MSG_CHANNEL_OPEN_FAILURE)
        if t == w.MSG_CHANNEL_OPEN_FAILURE:
            c.uint32()
            c.uint32()
            raise w.SshError(f"channel open failed: "
                             f"{c.string().decode()!r}")
        c.uint32()  # our id echoed
        their_id = c.uint32()
        their_window = c.uint32()
        their_maxpkt = max(1024, min(c.uint32() or 32768, 32768))

        ep.send_packet(bytes([w.MSG_CHANNEL_REQUEST])
                       + struct.pack(">I", their_id)
                       + w.put_string(b"exec") + b"\x01"
                       + w.put_string(cmd.encode()))

        out, err = [], []
        exit_status = None
        sent_stdin = False
        eof_sent = False
        closed = False
        pending = stdin

        def try_send_stdin():
            nonlocal pending, their_window, eof_sent, sent_stdin
            while pending and their_window > 0:
                chunk = pending[:min(their_maxpkt, their_window)]
                pending = pending[len(chunk):]
                their_window -= len(chunk)
                ep.send_packet(bytes([w.MSG_CHANNEL_DATA])
                               + struct.pack(">I", their_id)
                               + w.put_string(chunk))
            if not pending and not eof_sent:
                ep.send_packet(bytes([w.MSG_CHANNEL_EOF])
                               + struct.pack(">I", their_id))
                eof_sent = True

        while not closed:
            t, c = ep.recv_msg()
            if t == w.MSG_GLOBAL_REQUEST:
                # e.g. OpenSSH's hostkeys-00@openssh.com right after
                # auth: refuse politely when a reply is wanted, never
                # treat as fatal (stock sshd sends these by default)
                c.string()
                if c.boolean():
                    ep.send_packet(bytes([w.MSG_REQUEST_FAILURE]))
                continue
            if t in (w.MSG_REQUEST_SUCCESS, w.MSG_REQUEST_FAILURE):
                continue
            if t == w.MSG_CHANNEL_SUCCESS:
                # exec accepted: ship stdin now
                if not sent_stdin:
                    sent_stdin = True
                    try_send_stdin()
            elif t == w.MSG_CHANNEL_FAILURE:
                raise w.SshError(f"exec rejected: {cmd!r}")
            elif t == w.MSG_CHANNEL_WINDOW_ADJUST:
                c.uint32()
                their_window += c.uint32()
                if sent_stdin:
                    try_send_stdin()
            elif t == w.MSG_CHANNEL_DATA:
                c.uint32()
                out.append(c.string())
            elif t == w.MSG_CHANNEL_EXTENDED_DATA:
                c.uint32()
                c.uint32()  # data type (1 = stderr)
                err.append(c.string())
            elif t == w.MSG_CHANNEL_REQUEST:
                c.uint32()
                rtype = c.string()
                c.boolean()
                if rtype == b"exit-status":
                    exit_status = c.uint32()
            elif t == w.MSG_CHANNEL_EOF:
                pass
            elif t == w.MSG_CHANNEL_CLOSE:
                ep.send_packet(bytes([w.MSG_CHANNEL_CLOSE])
                               + struct.pack(">I", their_id))
                closed = True
            else:
                raise w.SshError(f"unexpected channel message {t}")
        out_b = b"".join(out)
        return {"exit": exit_status if exit_status is not None else -1,
                "out": out_b if raw else out_b.decode(errors="replace"),
                "err": b"".join(err).decode(errors="replace")}

    # -- Remote operations --------------------------------------------------
    def execute(self, context: dict, action: dict) -> dict:
        res = self._exec(action["cmd"],
                         stdin=(action.get("in") or "").encode())
        return {**action, **res}

    def upload(self, context: dict, local_paths, remote_path,
               opts: Optional[dict] = None) -> None:
        import os
        from .core import escape
        if isinstance(local_paths, (str, bytes)):
            local_paths = [local_paths]
        # scp semantics: several sources mean remote_path is a
        # DIRECTORY (each file lands under its basename); one source
        # writes remote_path itself
        many = len(local_paths) > 1
        for lp in local_paths:
            with open(lp, "rb") as f:
                data = f.read()
            dest = (f"{remote_path}/{os.path.basename(str(lp))}"
                    if many else str(remote_path))
            res = self._exec(f"cat > {escape(dest)}", stdin=data)
            if res["exit"] != 0:
                raise w.SshError(
                    f"upload to {dest!r} failed: {res['err']}")

    def download(self, context: dict, remote_paths, local_path,
                 opts: Optional[dict] = None) -> None:
        import os
        from .core import escape
        if isinstance(remote_paths, (str, bytes)):
            remote_paths = [remote_paths]
        for rp in remote_paths:
            # byte-faithful: logs/AOFs aren't UTF-8; decode-replace
            # here would silently corrupt them
            res = self._exec(f"cat {escape(str(rp))}", raw=True)
            if res["exit"] != 0:
                raise w.SshError(
                    f"download of {rp!r} failed: {res['err']}")
            dest = local_path
            if os.path.isdir(local_path):
                dest = os.path.join(local_path,
                                    os.path.basename(str(rp)))
            with open(dest, "wb") as f:
                f.write(res["out"])


def remote() -> NativeSSHRemote:
    return NativeSSHRemote()
