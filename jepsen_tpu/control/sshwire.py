"""A from-scratch SSH-2 wire engine (client AND server halves).

The reference ships two complete SSH stacks (clj-ssh and sshj, with an
scp fallback) because SSH transport flakiness is its top operational
pain (jepsen/src/jepsen/control/sshj.clj:70-79 works around an sshj
EOF bug; SURVEY "hard parts" #5). This package's first transport is
the OpenSSH CLI (control/sshcli.py); this module is the INDEPENDENT
second stack — the same discipline as the pgwire/BSON/RESP/AMQP
codecs: the protocol itself, implemented from the RFCs on
`cryptography` primitives, with no ssh binary or library involved.

Scope (deliberately one strong cipher suite, not a menu):
  * transport (RFC 4253): version exchange, binary packet protocol,
    curve25519-sha256 key exchange (RFC 8731), ssh-ed25519 host keys,
    aes128-ctr encryption with hmac-sha2-256 integrity;
  * userauth (RFC 4252): password (and "none" probing);
  * connection (RFC 4254): session channels, exec requests, stdin
    streaming, stdout/stderr demux, exit-status, window accounting.

`SshEndpoint` carries the shared packet/crypto state machine;
`client_handshake` / `server_handshake` drive the asymmetric halves.
The client-side Remote lives in control/sshnative.py; the loopback
test server in control/minisshd.py.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import socket
import struct
from typing import Optional

from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey, Ed25519PublicKey)
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey, X25519PublicKey)
from cryptography.hazmat.primitives.ciphers import (Cipher, algorithms,
                                                    modes)

VERSION = b"SSH-2.0-jepsen_tpu_0.1"

# message numbers (RFC 4250)
MSG_DISCONNECT = 1
MSG_IGNORE = 2
MSG_UNIMPLEMENTED = 3
MSG_DEBUG = 4
MSG_SERVICE_REQUEST = 5
MSG_SERVICE_ACCEPT = 6
MSG_KEXINIT = 20
MSG_NEWKEYS = 21
MSG_KEX_ECDH_INIT = 30
MSG_KEX_ECDH_REPLY = 31
MSG_USERAUTH_REQUEST = 50
MSG_USERAUTH_FAILURE = 51
MSG_USERAUTH_SUCCESS = 52
MSG_USERAUTH_BANNER = 53
MSG_GLOBAL_REQUEST = 80
MSG_REQUEST_SUCCESS = 81
MSG_REQUEST_FAILURE = 82
MSG_CHANNEL_OPEN = 90
MSG_CHANNEL_OPEN_CONFIRMATION = 91
MSG_CHANNEL_OPEN_FAILURE = 92
MSG_CHANNEL_WINDOW_ADJUST = 93
MSG_CHANNEL_DATA = 94
MSG_CHANNEL_EXTENDED_DATA = 95
MSG_CHANNEL_EOF = 96
MSG_CHANNEL_CLOSE = 97
MSG_CHANNEL_REQUEST = 98
MSG_CHANNEL_SUCCESS = 99
MSG_CHANNEL_FAILURE = 100

KEX_ALG = b"curve25519-sha256"
HOSTKEY_ALG = b"ssh-ed25519"
CIPHER_ALG = b"aes128-ctr"
MAC_ALG = b"hmac-sha2-256"
COMP_ALG = b"none"


class SshError(Exception):
    pass


# -- primitive encoders (RFC 4251 §5) ---------------------------------------

def put_string(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


def put_mpint(n: int) -> bytes:
    if n == 0:
        return put_string(b"")
    b = n.to_bytes((n.bit_length() + 7) // 8, "big")
    if b[0] & 0x80:  # positive numbers need a leading zero bit
        b = b"\x00" + b
    return put_string(b)


def put_namelist(*names: bytes) -> bytes:
    return put_string(b",".join(names))


class Cursor:
    def __init__(self, b: bytes, i: int = 0):
        self.b = b
        self.i = i

    def byte(self) -> int:
        v = self.b[self.i]
        self.i += 1
        return v

    def boolean(self) -> bool:
        return self.byte() != 0

    def uint32(self) -> int:
        v = struct.unpack_from(">I", self.b, self.i)[0]
        self.i += 4
        return v

    def string(self) -> bytes:
        n = self.uint32()
        v = self.b[self.i:self.i + n]
        self.i += n
        return v

    def namelist(self) -> list:
        return self.string().split(b",")


# -- the shared endpoint ----------------------------------------------------

class SshEndpoint:
    """One side of an SSH-2 connection: packet framing, the
    aes128-ctr/hmac-sha2-256 state after NEWKEYS, and kex plumbing.
    `server` flips which derived-key halves encrypt vs decrypt."""

    def __init__(self, sock: socket.socket, server: bool = False):
        self.sock = sock
        self.rf = sock.makefile("rb")
        self.server = server
        self.seq_out = 0
        self.seq_in = 0
        self.enc = None       # outgoing cipher context
        self.dec = None       # incoming cipher context
        self.mac_out: Optional[bytes] = None
        self.mac_in: Optional[bytes] = None
        self.session_id: Optional[bytes] = None
        self.local_version = VERSION
        self.remote_version: Optional[bytes] = None

    # -- version exchange ---------------------------------------------------
    def exchange_versions(self):
        self.sock.sendall(self.local_version + b"\r\n")
        # the peer may send banner lines before its version string
        for _ in range(32):
            line = self.rf.readline(512)
            if not line:
                raise SshError("peer closed before version exchange")
            if line.startswith(b"SSH-"):
                self.remote_version = line.strip()
                if not line.startswith(b"SSH-2."):
                    raise SshError(
                        f"unsupported protocol {line.strip()!r}")
                return
        raise SshError("no SSH version line within 32 lines")

    # -- binary packet protocol (RFC 4253 §6) -------------------------------
    def send_packet(self, payload: bytes):
        block = 16 if self.enc is not None else 8
        # total (len field + padlen field + payload + padding) must be
        # a multiple of the block size, padding >= 4
        pad = block - ((5 + len(payload)) % block)
        if pad < 4:
            pad += block
        packet = (struct.pack(">IB", 1 + len(payload) + pad, pad)
                  + payload + os.urandom(pad))
        if self.enc is None:
            self.sock.sendall(packet)
        else:
            mac = _hmac.new(self.mac_out,
                            struct.pack(">I", self.seq_out) + packet,
                            hashlib.sha256).digest()
            self.sock.sendall(self.enc.update(packet) + mac)
        self.seq_out = (self.seq_out + 1) & 0xFFFFFFFF

    def _read_exact(self, n: int) -> bytes:
        b = self.rf.read(n)
        if b is None or len(b) < n:
            raise SshError("connection closed mid-packet")
        return b

    def recv_packet(self) -> bytes:
        if self.dec is None:
            head = self._read_exact(5)
            length, pad = struct.unpack(">IB", head)
            rest = self._read_exact(length - 1)
            payload = rest[:length - 1 - pad]
        else:
            first = self.dec.update(self._read_exact(16))
            length, pad = struct.unpack(">IB", first[:5])
            if length > 1 << 20:
                raise SshError(f"absurd packet length {length}")
            rest_ct = self._read_exact(4 + length - 16)
            rest = self.dec.update(rest_ct)
            packet = first + rest
            mac = self._read_exact(32)
            want = _hmac.new(self.mac_in,
                             struct.pack(">I", self.seq_in) + packet,
                             hashlib.sha256).digest()
            if not _hmac.compare_digest(mac, want):
                raise SshError("MAC verification failed")
            payload = packet[5:5 + length - 1 - pad]
        self.seq_in = (self.seq_in + 1) & 0xFFFFFFFF
        return payload

    def recv_msg(self, *want: int) -> tuple[int, Cursor]:
        """Next non-transport-noise message; asserts type if `want`."""
        while True:
            p = self.recv_packet()
            t = p[0]
            if t in (MSG_IGNORE, MSG_DEBUG):
                continue
            if t == MSG_UNIMPLEMENTED:
                raise SshError("peer: unimplemented")
            if t == MSG_DISCONNECT:
                c = Cursor(p, 1)
                c.uint32()
                raise SshError(
                    f"peer disconnected: {c.string().decode()!r}")
            if want and t not in want:
                raise SshError(f"expected msg {want}, got {t}")
            return t, Cursor(p, 1)

    # -- kex ----------------------------------------------------------------
    def kexinit_payload(self) -> bytes:
        return (bytes([MSG_KEXINIT]) + os.urandom(16)
                + put_namelist(KEX_ALG)
                + put_namelist(HOSTKEY_ALG)
                + put_namelist(CIPHER_ALG) + put_namelist(CIPHER_ALG)
                + put_namelist(MAC_ALG) + put_namelist(MAC_ALG)
                + put_namelist(COMP_ALG) + put_namelist(COMP_ALG)
                + put_namelist() + put_namelist()
                + b"\x00" + struct.pack(">I", 0))

    @staticmethod
    def check_kexinit(payload: bytes):
        """The peer must support our single suite (first-match rule)."""
        c = Cursor(payload, 1)
        c.i += 16  # cookie
        lists = [c.namelist() for _ in range(8)]
        for alg, offered in zip(
                (KEX_ALG, HOSTKEY_ALG, CIPHER_ALG, CIPHER_ALG,
                 MAC_ALG, MAC_ALG, COMP_ALG, COMP_ALG), lists):
            if alg not in offered:
                raise SshError(
                    f"peer doesn't offer {alg.decode()}: {offered}")

    def activate_keys(self, K: int, H: bytes):
        """Derive + switch on the cipher/MAC state (RFC 4253 §7.2)."""
        if self.session_id is None:
            self.session_id = H

        def kdf(x: bytes, size: int) -> bytes:
            base = put_mpint(K) + H
            out = hashlib.sha256(base + x + self.session_id).digest()
            while len(out) < size:
                out += hashlib.sha256(base + out).digest()
            return out[:size]

        iv_c2s = kdf(b"A", 16)
        iv_s2c = kdf(b"B", 16)
        key_c2s = kdf(b"C", 16)
        key_s2c = kdf(b"D", 16)
        mac_c2s = kdf(b"E", 32)
        mac_s2c = kdf(b"F", 32)
        mk_enc = lambda k, iv: Cipher(  # noqa: E731
            algorithms.AES(k), modes.CTR(iv)).encryptor()
        mk_dec = lambda k, iv: Cipher(  # noqa: E731
            algorithms.AES(k), modes.CTR(iv)).decryptor()
        if self.server:
            self.enc = mk_enc(key_s2c, iv_s2c)
            self.dec = mk_dec(key_c2s, iv_c2s)
            self.mac_out, self.mac_in = mac_s2c, mac_c2s
        else:
            self.enc = mk_enc(key_c2s, iv_c2s)
            self.dec = mk_dec(key_s2c, iv_s2c)
            self.mac_out, self.mac_in = mac_c2s, mac_s2c

    def close(self):
        try:
            self.rf.close()
            self.sock.close()
        except OSError:
            pass


def exchange_hash(client_version: bytes, server_version: bytes,
                  client_kexinit: bytes, server_kexinit: bytes,
                  host_key_blob: bytes, q_c: bytes, q_s: bytes,
                  K: int) -> bytes:
    return hashlib.sha256(
        put_string(client_version) + put_string(server_version)
        + put_string(client_kexinit) + put_string(server_kexinit)
        + put_string(host_key_blob) + put_string(q_c)
        + put_string(q_s) + put_mpint(K)).digest()


def ed25519_blob(pub: Ed25519PublicKey) -> bytes:
    from cryptography.hazmat.primitives.serialization import (
        Encoding, PublicFormat)
    raw = pub.public_bytes(Encoding.Raw, PublicFormat.Raw)
    return put_string(HOSTKEY_ALG) + put_string(raw)


def client_handshake(ep: SshEndpoint,
                     expected_hostkey: Optional[bytes] = None) -> bytes:
    """Version + kex + NEWKEYS from the client side. Returns the
    server's raw ed25519 host key (32 B) for trust-on-first-use /
    pinning by the caller."""
    ep.exchange_versions()
    my_kexinit = ep.kexinit_payload()
    ep.send_packet(my_kexinit)
    t, _ = ep.recv_msg(MSG_KEXINIT)
    their_kexinit = bytes([t]) + _.b[1:]
    ep.check_kexinit(their_kexinit)

    eph = X25519PrivateKey.generate()
    from cryptography.hazmat.primitives.serialization import (
        Encoding, PublicFormat)
    q_c = eph.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
    ep.send_packet(bytes([MSG_KEX_ECDH_INIT]) + put_string(q_c))
    _, c = ep.recv_msg(MSG_KEX_ECDH_REPLY)
    host_blob = c.string()
    q_s = c.string()
    sig_blob = c.string()

    hb = Cursor(host_blob)
    if hb.string() != HOSTKEY_ALG:
        raise SshError("host key is not ssh-ed25519")
    host_raw = hb.string()
    if expected_hostkey is not None and host_raw != expected_hostkey:
        raise SshError("HOST KEY MISMATCH (possible MITM)")

    shared = eph.exchange(X25519PublicKey.from_public_bytes(q_s))
    K = int.from_bytes(shared, "big")
    H = exchange_hash(ep.local_version, ep.remote_version,
                      my_kexinit, their_kexinit, host_blob,
                      q_c, q_s, K)
    sb = Cursor(sig_blob)
    if sb.string() != HOSTKEY_ALG:
        raise SshError("signature is not ssh-ed25519")
    Ed25519PublicKey.from_public_bytes(host_raw).verify(
        sb.string(), H)  # raises InvalidSignature on tampering

    ep.send_packet(bytes([MSG_NEWKEYS]))
    ep.recv_msg(MSG_NEWKEYS)
    ep.activate_keys(K, H)
    return host_raw


def server_handshake(ep: SshEndpoint, host_key: Ed25519PrivateKey):
    """The mirror-image half for the loopback server."""
    ep.exchange_versions()
    my_kexinit = ep.kexinit_payload()
    ep.send_packet(my_kexinit)
    t, c = ep.recv_msg(MSG_KEXINIT)
    their_kexinit = bytes([t]) + c.b[1:]
    ep.check_kexinit(their_kexinit)

    _, c = ep.recv_msg(MSG_KEX_ECDH_INIT)
    q_c = c.string()
    eph = X25519PrivateKey.generate()
    from cryptography.hazmat.primitives.serialization import (
        Encoding, PublicFormat)
    q_s = eph.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
    shared = eph.exchange(X25519PublicKey.from_public_bytes(q_c))
    K = int.from_bytes(shared, "big")
    host_blob = ed25519_blob(host_key.public_key())
    # NB: client/server version+kexinit order in H is C then S
    H = exchange_hash(ep.remote_version, ep.local_version,
                      their_kexinit, my_kexinit, host_blob,
                      q_c, q_s, K)
    sig = host_key.sign(H)
    ep.send_packet(bytes([MSG_KEX_ECDH_REPLY]) + put_string(host_blob)
                   + put_string(q_s)
                   + put_string(put_string(HOSTKEY_ALG)
                                + put_string(sig)))
    ep.send_packet(bytes([MSG_NEWKEYS]))
    ep.recv_msg(MSG_NEWKEYS)
    ep.activate_keys(K, H)
