"""Node-side admin toolkit: daemons, downloads, archives, tmp files.

Capability parity with jepsen.control.util
(`jepsen/src/jepsen/control/util.clj`): await-tcp-port (:14), file
predicates (:32-61), tmp-file!/tmp-dir! (:63-87), write-file! (:88),
wget!/cached-wget! (:113-198), install-archive! (:199-276),
grepkill! (:286-308), start-daemon!/stop-daemon! via start-stop-daemon
(:310-386), daemon-running? (:386-397), signal! (:399-403).

All functions run against the currently bound control session.
"""

from __future__ import annotations

import logging
import os
import time as _time
from typing import Optional, Sequence

from . import cd, exec_, exec_star, su
from .core import NonzeroExit, env as make_env, escape, lit

log = logging.getLogger("jepsen_tpu.control.util")


def meh(f, *args, **kw):
    """Run f, returning None instead of raising (util.clj's meh)."""
    try:
        return f(*args, **kw)
    except Exception:  # noqa: BLE001
        return None


def await_tcp_port(port: int, host: str = "localhost",
                   timeout_s: float = 60, interval_s: float = 0.5) -> None:
    """Wait for a TCP port to open on the node (control/util.clj:14-30)."""
    deadline = _time.monotonic() + timeout_s
    while True:
        try:
            exec_("bash", "-c",
                  f"exec 3<>/dev/tcp/{host}/{port}")
            return
        except NonzeroExit:
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"port {host}:{port} did not open in {timeout_s}s")
            _time.sleep(interval_s)


def file_exists(path: str) -> bool:
    """exists? (control/util.clj:38-43)."""
    try:
        exec_("test", "-e", path)
        return True
    except NonzeroExit:
        return False


def is_file(path: str) -> bool:
    try:
        exec_("test", "-f", path)
        return True
    except NonzeroExit:
        return False


def ls(dir: str = ".") -> list:
    """ls (control/util.clj:45-51)."""
    out = exec_("ls", dir)
    return [l for l in out.split("\n") if l]


def ls_full(dir: str) -> list:
    d = dir if dir.endswith("/") else dir + "/"
    return [d + f for f in ls(d)]


def tmp_file(ext: str = "") -> str:
    """Create a fresh random remote file (control/util.clj:63-76)."""
    suffix = f" --suffix={escape(ext)}" if ext else ""
    return exec_star(f"mktemp /tmp/jepsen-tmp-XXXXXX{suffix}")


def tmp_dir() -> str:
    """Create a fresh random remote directory (control/util.clj:78-86)."""
    return exec_star("mktemp -d /tmp/jepsen-tmp-XXXXXX")


def write_file(content: str, path: str) -> str:
    """Write a string to a remote file (control/util.clj:88-111)."""
    from . import upload_text
    upload_text(content, path)
    return path


def wget(url: str, dest: Optional[str] = None, force: bool = False) -> str:
    """Download a URL on the node (control/util.clj:133-160)."""
    filename = dest or url.split("/")[-1].split("?")[0]
    if force:
        meh(exec_, "rm", "-f", filename)
    if not file_exists(filename):
        exec_("wget", "-O", filename, url)
    return filename


CACHE_DIR = "/tmp/jepsen/cache"


def cached_wget(url: str, force: bool = False) -> str:
    """Download with a node-local cache keyed by URL
    (control/util.clj:167-198)."""
    import hashlib
    key = hashlib.sha256(url.encode()).hexdigest()[:32]
    path = f"{CACHE_DIR}/{key}"
    if force:
        meh(exec_, "rm", "-f", path)
    if not file_exists(path):
        exec_("mkdir", "-p", CACHE_DIR)
        tmp = tmp_file()
        exec_("wget", "-O", tmp, url)
        exec_("mv", tmp, path)
    return path


def install_archive(url: str, dest: str, force: bool = False,
                    user: Optional[str] = None) -> str:
    """Download and extract a tarball/zip to dest
    (control/util.clj:199-276). file:// URLs are used as-is."""
    local = url[len("file://"):] if url.startswith("file://") \
        else cached_wget(url, force=force)
    exec_("rm", "-rf", dest)
    exec_("mkdir", "-p", dest)
    tmp = tmp_dir()
    try:
        if url.rstrip("/").endswith(".zip"):
            exec_("unzip", local, "-d", tmp)
        else:
            exec_("tar", "--no-same-owner", "--no-same-permissions",
                  "--extract", "--file", local, "--directory", tmp)
        entries = ls(tmp)
        if len(entries) == 1 and not is_file(f"{tmp}/{entries[0]}"):
            # single top-level directory: move its contents
            src = f"{tmp}/{entries[0]}"
        else:
            # flat archive (possibly a single file): move everything
            src = tmp
        # Move contents into dest; dotfiles may legitimately be absent.
        exec_star(f"mv {escape(src)}/* {escape(dest)}/")
        exec_star(f"mv {escape(src)}/.[!.]* {escape(dest)}/ "
                  "2>/dev/null || true")
        if user:
            exec_("chown", "-R", user, dest)
    finally:
        meh(exec_, "rm", "-rf", tmp)
    return dest


def grepkill(pattern: str, signal: str = "9") -> None:
    """Kill all processes matching a pattern (control/util.clj:286-308).
    Deliberately NOT pkill -f: the remote bash/sudo wrapper's own command
    line contains the pattern and would signal itself (the reference uses
    ps | grep -v grep for exactly this reason)."""
    # $$ exclusion: the wrapping shell's own command line contains the
    # pattern (fatal under the localexec remote, where bash -c IS the
    # node process; merely cosmetic over SSH)
    meh(exec_star,
        f"ps -ef | grep {escape(pattern)} | grep -v grep "
        f"| awk -v self=$$ '$2 != self {{print $2}}' "
        f"| xargs --no-run-if-empty kill -s {escape(str(signal))}")


def signal(process_name: str, sig: str) -> str:
    """Send a signal to a named process by COMM field
    (control/util.clj:399-403). pkill without -f matches only the
    process name, so the shell wrapper is safe."""
    meh(exec_, "pkill", "--signal", str(sig), process_name)
    return "signaled"


def start_daemon(opts: dict, bin: str, *args) -> str:
    """Start a daemon under start-stop-daemon, logging to opts["logfile"]
    (control/util.clj:310-368). Returns "started" or "already-running"."""
    e = make_env(opts.get("env"))
    logfile = opts["logfile"]
    ssd = ["start-stop-daemon", "--start"]
    if opts.get("background?", True):
        ssd += ["--background", "--no-close"]
    if opts.get("pidfile") and opts.get("make-pidfile?", True):
        ssd += ["--make-pidfile"]
    if opts.get("match-executable?", True):
        ssd += ["--exec", opts.get("exec", bin)]
    if opts.get("match-process-name?", False):
        ssd += ["--name", opts.get("process-name", os.path.basename(bin))]
    if opts.get("pidfile"):
        ssd += ["--pidfile", opts["pidfile"]]
    if opts.get("chdir"):
        ssd += ["--chdir", opts["chdir"]]
    ssd += ["--startas", bin, "--", *args]
    log.info("Starting %s", os.path.basename(bin))
    exec_("echo", lit("`date +'%Y-%m-%d %H:%M:%S'`"),
          f"Jepsen starting {escape(e)} {bin} {escape(list(args))}",
          lit(">>"), logfile)
    try:
        prefix = [e] if e else []
        exec_(*prefix, *ssd, lit(">>"), logfile, lit("2>&1"))
        return "started"
    except NonzeroExit as err:
        if err.result.get("exit") == 1:
            return "already-running"
        raise


def stop_daemon(cmd_or_pidfile: str, pidfile: Optional[str] = None) -> None:
    """Kill a daemon by pidfile, or by command name + pidfile
    (control/util.clj:369-385)."""
    if pidfile is None:
        pf = cmd_or_pidfile
        if file_exists(pf):
            log.info("Stopping %s", pf)
            # the pidfile may vanish between the check and the read (a
            # concurrent nemesis kill + teardown, both stopping)
            pid = (meh(exec_, "cat", pf) or "").strip()
            if pid:
                meh(exec_, "kill", "-9", pid)
                meh(exec_, "rm", "-rf", pf)
            elif not file_exists(pf):
                pass  # vanished mid-race: the other stopper owns it
            else:
                # cat failed while the file still exists (transient
                # remote error?) — leave the pidfile so a later stop
                # can still find the daemon
                log.warning("could not read %s; daemon may still be "
                            "running", pf)
    else:
        log.info("Stopping %s", cmd_or_pidfile)
        meh(exec_, "killall", "-9", "-w", cmd_or_pidfile)
        if pidfile:
            meh(exec_, "rm", "-rf", pidfile)


def daemon_running(pidfile: str):
    """True/False/None per control/util.clj:386-397."""
    pid = meh(exec_, "cat", pidfile)
    if pid is None or pid == "":
        return None
    try:
        exec_("ps", "-o", "pid=", "-p", pid.strip())
        return True
    except NonzeroExit:
        return False
