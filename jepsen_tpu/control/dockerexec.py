"""Remote over `docker exec` / `docker cp` — for containerized clusters
(parity with jepsen.control.docker, `control/docker.clj:1-92`)."""

from __future__ import annotations

import os
import subprocess
from typing import Optional

from .core import Remote, wrap_sudo


class DockerRemote(Remote):
    def __init__(self, container: Optional[str] = None):
        self.container = container

    def connect(self, conn_spec):
        return DockerRemote(conn_spec["host"])

    def execute(self, context, action):
        action = wrap_sudo(context, action)
        res = subprocess.run(
            ["docker", "exec", "-i", self.container, "bash", "-c",
             action["cmd"]],
            input=(action.get("in") or "").encode() if action.get("in")
            else None,
            capture_output=True, timeout=action.get("timeout"))
        return {**action, "exit": res.returncode,
                "out": res.stdout.decode(errors="replace"),
                "err": res.stderr.decode(errors="replace"),
                "action": action}

    def upload(self, context, local_paths, remote_path, opts=None):
        if isinstance(local_paths, (str, os.PathLike)):
            local_paths = [local_paths]
        for p in local_paths:
            subprocess.run(["docker", "cp", str(p),
                            f"{self.container}:{remote_path}"], check=True)

    def download(self, context, remote_paths, local_path, opts=None):
        if isinstance(remote_paths, (str, os.PathLike)):
            remote_paths = [remote_paths]
        for p in remote_paths:
            subprocess.run(["docker", "cp", f"{self.container}:{p}",
                            str(local_path)], check=True)


def remote() -> DockerRemote:
    return DockerRemote()
