"""IP lookup helpers on nodes (parity with jepsen.control.net,
`jepsen/src/jepsen/control/net.clj:1-53`)."""

from __future__ import annotations

import socket
from functools import lru_cache
from typing import Optional

from . import exec_, state


@lru_cache(maxsize=1024)
def _resolve(node: str) -> str:
    return socket.gethostbyname(node)


def ip(node: str) -> str:
    """The IP address for a node name. Resolved on the control node first
    (cheap); falls back to `getent` on the current session's host
    (control/net.clj's ip). Unresolvable names come back unchanged —
    best effort: scripted/dummy remotes have no resolver, and a real
    cluster with broken DNS should surface the daemon's own bind error
    rather than a harness crash."""
    try:
        return _resolve(node)
    except OSError:
        try:
            out = exec_("getent", "hosts", node)
            return out.split()[0]
        except Exception:  # noqa: BLE001 — no resolver on this remote
            return node


def local_ip() -> str:
    """The bound node's own IP (control/net.clj's local-ip)."""
    return exec_("hostname", "-I").split()[0]


def control_ip() -> Optional[str]:
    """The control node's IP as seen from the cluster
    (control/net.clj's control-ip): the source address of a route
    towards the current host."""
    host = state.host
    if host is None:
        return socket.gethostbyname(socket.gethostname())
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((_resolve(host), 22))
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()
