"""Auto-retrying remote wrapper (parity with jepsen.control.retry,
`control/retry.clj:1-72`): SSH connections flake, so retry failed
actions a few times with backoff, reconnecting on error. Connection
state lives in a `jepsen_tpu.reconnect.Wrapper` — concurrent users share
the session under a read lock, and reconnects are exclusive, exactly as
the reference builds retry on jepsen.reconnect."""

from __future__ import annotations

import logging
import time as _time
from typing import Optional

from ..reconnect import Wrapper
from .core import Remote

log = logging.getLogger("jepsen_tpu.control.retry")

RETRIES = 5          # control/retry.clj:15-17
BACKOFF_S = 0.1      # control/retry.clj:19-21


class RetryRemote(Remote):
    def __init__(self, remote: Remote, conn_spec: Optional[dict] = None,
                 wrapper: Optional[Wrapper] = None):
        self.inner = remote
        self.conn_spec = conn_spec
        self.wrapper = wrapper

    def connect(self, conn_spec):
        w = Wrapper(lambda: self.inner.connect(conn_spec),
                    lambda s: s.disconnect(),
                    name=str(conn_spec.get("host")))
        last = None
        for _ in range(RETRIES):
            try:
                w.open()
                return RetryRemote(self.inner, conn_spec, w)
            except Exception as e:  # noqa: BLE001
                last = e
                _time.sleep(BACKOFF_S)
        raise last  # type: ignore[misc]

    def disconnect(self):
        if self.wrapper:
            self.wrapper.close()

    def _with_retry(self, f):
        last = None
        for _ in range(RETRIES):
            try:
                return self.wrapper.with_conn(f)
            except Exception as e:  # noqa: BLE001
                last = e
                log.warning("remote action failed (%s); reconnecting", e)
                _time.sleep(BACKOFF_S)
                try:
                    self.wrapper.reopen()
                except Exception as ce:  # noqa: BLE001
                    last = ce
        raise last  # type: ignore[misc]

    def execute(self, context, action):
        return self._with_retry(lambda s: s.execute(context, action))

    def upload(self, context, local_paths, remote_path, opts=None):
        return self._with_retry(
            lambda s: s.upload(context, local_paths, remote_path, opts))

    def download(self, context, remote_paths, local_path, opts=None):
        return self._with_retry(
            lambda s: s.download(context, remote_paths, local_path, opts))


def remote(inner: Remote) -> RetryRemote:
    return RetryRemote(inner)
