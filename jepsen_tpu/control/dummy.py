"""A no-op remote for cluster-free tests.

Parity with the reference's `:dummy?` mode (`control.clj:40`, exercised
by `jepsen/test/jepsen/core_test.clj:55-58` via `:ssh {:dummy? true}`):
every command "succeeds" with empty output. Commands are recorded on the
shared `log` list so tests can assert orchestration behavior.
"""

from __future__ import annotations

from typing import Optional

from .core import Remote


class DummyRemote(Remote):
    def __init__(self, log: Optional[list] = None):
        self.log = log if log is not None else []
        self.host = None

    def connect(self, conn_spec):
        # type(self): scripted-subclass remotes (test stubs overriding
        # execute) must survive the connect
        r = type(self)(self.log)
        r.host = conn_spec.get("host")
        return r

    def execute(self, context, action):
        self.log.append((self.host, action.get("cmd")))
        return {**action, "exit": 0, "out": "", "err": "",
                "action": action}

    def upload(self, context, local_paths, remote_path, opts=None):
        self.log.append((self.host, ("upload", local_paths, remote_path)))

    def download(self, context, remote_paths, local_path, opts=None):
        self.log.append((self.host, ("download", remote_paths, local_path)))


def remote(log: Optional[list] = None) -> DummyRemote:
    return DummyRemote(log)
