"""A loopback SSH-2 server for exercising the native transport.

No sshd ships in this environment (there is no ssh binary at all), so
the from-scratch client (control/sshnative.py) is tested the way the
mini DB servers test their suites: against an in-repo server speaking
the same RFC subset through the SAME wire engine's server half
(control/sshwire.py server_handshake). Binds 127.0.0.1 only, requires
the per-instance random password, and executes commands via bash in a
caller-chosen working directory — a real remote-execution surface for
the control-plane tests, not a mock.
"""

from __future__ import annotations

import secrets
import socket
import struct
import subprocess
import threading
from typing import Optional

from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey)
from cryptography.hazmat.primitives.serialization import (Encoding,
                                                          PublicFormat)

from . import sshwire as w


class MiniSshd:
    def __init__(self, cwd: str = ".", password: Optional[str] = None,
                 user: str = "jepsen"):
        self.cwd = cwd
        self.user = user
        self.password = password or secrets.token_hex(12)
        self.host_key = Ed25519PrivateKey.generate()
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._serve, daemon=True)

    @property
    def host_key_raw(self) -> bytes:
        return self.host_key.public_key().public_bytes(
            Encoding.Raw, PublicFormat.Raw)

    def start(self) -> "MiniSshd":
        self.thread.start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self.sock.close()
        except OSError:
            pass

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._session, args=(conn,),
                             daemon=True).start()

    def _session(self, conn: socket.socket):
        conn.settimeout(60)
        ep = w.SshEndpoint(conn, server=True)
        try:
            w.server_handshake(ep, self.host_key)
            self._userauth(ep)
            self._connection(ep)
        except (w.SshError, OSError, ValueError):
            pass
        finally:
            ep.close()

    def _userauth(self, ep: w.SshEndpoint):
        _, c = ep.recv_msg(w.MSG_SERVICE_REQUEST)
        if c.string() != b"ssh-userauth":
            raise w.SshError("expected ssh-userauth")
        ep.send_packet(bytes([w.MSG_SERVICE_ACCEPT])
                       + w.put_string(b"ssh-userauth"))
        for _ in range(8):
            _, c = ep.recv_msg(w.MSG_USERAUTH_REQUEST)
            user = c.string().decode()
            c.string()  # service
            method = c.string()
            if method == b"password":
                c.boolean()
                pw = c.string().decode()
                if user == self.user and pw == self.password:
                    ep.send_packet(bytes([w.MSG_USERAUTH_SUCCESS]))
                    return
            ep.send_packet(bytes([w.MSG_USERAUTH_FAILURE])
                           + w.put_namelist(b"password") + b"\x00")
        raise w.SshError("too many auth attempts")

    def _connection(self, ep: w.SshEndpoint):
        """Serve session channels until the peer disconnects.
        Channels are handled one at a time (the client multiplexes
        sequentially), each: exec request -> buffer stdin to EOF ->
        run -> stream stdout/stderr -> exit-status -> close."""
        while True:
            t, c = ep.recv_msg()
            if t != w.MSG_CHANNEL_OPEN:
                continue  # global requests etc.: ignore
            ctype = c.string()
            their_id = c.uint32()
            c.uint32()  # their window (we send small frames anyway)
            c.uint32()
            if ctype != b"session":
                ep.send_packet(bytes([w.MSG_CHANNEL_OPEN_FAILURE])
                               + struct.pack(">II", their_id, 3)
                               + w.put_string(b"unsupported")
                               + w.put_string(b""))
                continue
            my_id = 0
            ep.send_packet(bytes([w.MSG_CHANNEL_OPEN_CONFIRMATION])
                           + struct.pack(">IIII", their_id, my_id,
                                         0x7FFFFFFF, 32768))
            self._channel(ep, their_id)

    def _channel(self, ep: w.SshEndpoint, their_id: int):
        cmd: Optional[str] = None
        stdin: list = []
        got_eof = False
        sent_close = False
        while True:
            t, c = ep.recv_msg()
            if t == w.MSG_CHANNEL_REQUEST:
                c.uint32()
                rtype = c.string()
                want_reply = c.boolean()
                if rtype == b"exec":
                    cmd = c.string().decode()
                    if want_reply:
                        ep.send_packet(bytes([w.MSG_CHANNEL_SUCCESS])
                                       + struct.pack(">I", their_id))
                elif want_reply:
                    ep.send_packet(bytes([w.MSG_CHANNEL_FAILURE])
                                   + struct.pack(">I", their_id))
            elif t == w.MSG_CHANNEL_DATA:
                c.uint32()
                stdin.append(c.string())
            elif t == w.MSG_CHANNEL_EOF:
                got_eof = True
            elif t == w.MSG_CHANNEL_CLOSE:
                # CLOSE is sent at most once per side (RFC 4254 §5.3);
                # _run already closed our half after exit-status — a
                # second CLOSE here would poison the NEXT channel
                if not sent_close:
                    ep.send_packet(bytes([w.MSG_CHANNEL_CLOSE])
                                   + struct.pack(">I", their_id))
                return
            if cmd is not None and got_eof:
                self._run(ep, their_id, cmd, b"".join(stdin))
                cmd = None  # wait for the peer's CLOSE
                sent_close = True

    def _run(self, ep: w.SshEndpoint, their_id: int, cmd: str,
             stdin: bytes):
        try:
            p = subprocess.run(["bash", "-c", cmd], input=stdin,
                               capture_output=True, cwd=self.cwd,
                               timeout=120)
            out, err, code = p.stdout, p.stderr, p.returncode
        except subprocess.TimeoutExpired:
            out, err, code = b"", b"command timed out\n", 124
        for i in range(0, len(out), 32000):
            ep.send_packet(bytes([w.MSG_CHANNEL_DATA])
                           + struct.pack(">I", their_id)
                           + w.put_string(out[i:i + 32000]))
        for i in range(0, len(err), 32000):
            ep.send_packet(bytes([w.MSG_CHANNEL_EXTENDED_DATA])
                           + struct.pack(">II", their_id, 1)
                           + w.put_string(err[i:i + 32000]))
        ep.send_packet(bytes([w.MSG_CHANNEL_REQUEST])
                       + struct.pack(">I", their_id)
                       + w.put_string(b"exit-status") + b"\x00"
                       + struct.pack(">I", code & 0xFFFFFFFF))
        ep.send_packet(bytes([w.MSG_CHANNEL_EOF])
                       + struct.pack(">I", their_id))
        ep.send_packet(bytes([w.MSG_CHANNEL_CLOSE])
                       + struct.pack(">I", their_id))
