"""SSH remote driving the OpenSSH client as a subprocess.

The reference ships two JVM SSH stacks (clj-ssh/JSch at
`jepsen/src/jepsen/control/clj_ssh.clj` and SSHJ at
`jepsen/src/jepsen/control/sshj.clj`). Here the system `ssh` binary is
the transport: a ControlMaster multiplexed connection per node gives
JSch-style session reuse without a Python SSH library, and `scp` handles
file transfer (the reference's scp remote, `control/scp.clj:59-139`).
Concurrency is capped per connection exactly as the reference caps
channels (8, clj_ssh.clj:87-94).
"""

from __future__ import annotations

import os
import subprocess
import tempfile
import threading
from typing import Optional

from .core import Remote, wrap_sudo

CONCURRENCY_LIMIT = 8  # concurrent actions per connection (clj_ssh.clj:87-94)


class SSHRemote(Remote):
    def __init__(self, conn_spec: Optional[dict] = None):
        self.spec = conn_spec or {}
        self.control_dir: Optional[str] = None
        self.sem = threading.Semaphore(CONCURRENCY_LIMIT)

    # -- connection management -------------------------------------------
    def connect(self, conn_spec):
        r = SSHRemote(conn_spec)
        r.control_dir = tempfile.mkdtemp(prefix="jepsen-ssh-")
        # Open the master connection eagerly so failures surface at
        # connect time, as the reference's remotes do.
        res = r._run(r._ssh_args() + ["true"])
        if res.returncode != 0:
            try:
                os.rmdir(r.control_dir)
            except OSError:
                pass
            raise ConnectionError(
                f"ssh connect to {conn_spec.get('host')} failed: "
                f"{res.stderr.decode(errors='replace')}")
        return r

    def disconnect(self):
        if self.control_dir:
            self._run(["ssh", "-o", f"ControlPath={self._control_path()}",
                       "-O", "exit", self._dest()], timeout=10)
            try:
                os.rmdir(self.control_dir)
            except OSError:
                pass

    def _control_path(self) -> str:
        return os.path.join(self.control_dir or "/tmp", "cm-%r@%h:%p")

    def _dest(self) -> str:
        user = self.spec.get("username") or "root"
        return f"{user}@{self.spec.get('host')}"

    def _common_opts(self) -> list:
        opts = ["-o", "BatchMode=yes",
                "-o", f"ControlPath={self._control_path()}",
                "-o", "ControlMaster=auto",
                "-o", "ControlPersist=60",
                "-o", "ConnectTimeout=10"]
        if str(self.spec.get("strict_host_key_checking", "yes")) in (
                "no", "false", "False"):
            opts += ["-o", "StrictHostKeyChecking=no",
                     "-o", "UserKnownHostsFile=/dev/null"]
        if self.spec.get("port"):
            opts += ["-p", str(self.spec["port"])]
        if self.spec.get("private_key_path"):
            opts += ["-i", str(self.spec["private_key_path"])]
        return opts

    def _ssh_args(self) -> list:
        return ["ssh"] + self._common_opts() + [self._dest()]

    def _run(self, args, input_bytes: Optional[bytes] = None,
             timeout: Optional[float] = None):
        return subprocess.run(args, input=input_bytes,
                              capture_output=True, timeout=timeout)

    # -- actions ----------------------------------------------------------
    def execute(self, context, action):
        action = wrap_sudo(context, action)
        with self.sem:
            res = self._run(self._ssh_args() + [action["cmd"]],
                            input_bytes=(action.get("in") or "").encode()
                            if action.get("in") else None,
                            timeout=action.get("timeout"))
        return {**action,
                "exit": res.returncode,
                "out": res.stdout.decode(errors="replace"),
                "err": res.stderr.decode(errors="replace"),
                "action": action}

    def _scp_args(self) -> list:
        args = ["scp", "-r"] + self._common_opts()
        if self.spec.get("port"):
            # scp uses -P for port
            i = args.index("-p")
            args[i] = "-P"
        return args

    def upload(self, context, local_paths, remote_path, opts=None):
        if isinstance(local_paths, (str, os.PathLike)):
            local_paths = [local_paths]
        with self.sem:
            res = self._run(self._scp_args() + [str(p) for p in local_paths]
                            + [f"{self._dest()}:{remote_path}"])
        if res.returncode != 0:
            raise IOError("scp upload failed: "
                          f"{res.stderr.decode(errors='replace')}")

    def download(self, context, remote_paths, local_path, opts=None):
        if isinstance(remote_paths, (str, os.PathLike)):
            remote_paths = [remote_paths]
        with self.sem:
            res = self._run(self._scp_args()
                            + [f"{self._dest()}:{p}" for p in remote_paths]
                            + [str(local_path)])
        if res.returncode != 0:
            raise IOError("scp download failed: "
                          f"{res.stderr.decode(errors='replace')}")


def remote() -> SSHRemote:
    return SSHRemote()
