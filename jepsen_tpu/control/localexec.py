"""A Remote that runs commands in local subprocesses.

The real-execution sibling of the dummy remote (`control/dummy.py`):
where dummy pretends every command succeeded, this one actually runs
them — `bash -c` under a per-node sandbox directory — so the entire
control algebra (escaping, cd, env prefixes, sudo-wrapped actions,
daemon management via `nodeutil.start_daemon`, real pids and signals)
is exercised against a live machine without SSH or containers. This is
the loopback integration tier the reference lacks (its control tests
need a reachable node and are tagged/skipped by default,
`jepsen/test/jepsen/control_test.clj`); suites like
`jepsen_tpu.dbs.toykv` use it to run a real networked DB cluster
in-process-tree.

Each "node" <host> is sandboxed under <root>/<host>/: commands run
with that working directory and JEPSEN_NODE / JEPSEN_NODE_DIR
exported; absolute paths in upload/download are rebased into the
sandbox so nodes stay isolated. Sudo is accepted but ignored — the
current user runs everything (matching the docker remote's stance,
control/docker.clj).
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import Optional

from .core import Remote

DEFAULT_TIMEOUT_S = 60.0


class LocalExecRemote(Remote):
    def __init__(self, root: str, timeout_s: float = DEFAULT_TIMEOUT_S):
        self.root = os.path.abspath(root)
        self.timeout_s = timeout_s
        self.host: Optional[str] = None

    # -- lifecycle ---------------------------------------------------------

    def connect(self, conn_spec):
        r = LocalExecRemote(self.root, self.timeout_s)
        r.host = conn_spec.get("host") or "local"
        os.makedirs(r.node_dir, exist_ok=True)
        return r

    @property
    def node_dir(self) -> str:
        return os.path.join(self.root, str(self.host))

    def _rebase(self, path: str) -> str:
        """Rebase an absolute path into the node sandbox; relative
        paths resolve against the sandbox root."""
        p = str(path)
        if os.path.isabs(p):
            return os.path.join(self.node_dir, p.lstrip("/"))
        return os.path.join(self.node_dir, p)

    # -- execution ---------------------------------------------------------

    def execute(self, context, action):
        env = dict(os.environ)
        env["JEPSEN_NODE"] = str(self.host)
        env["JEPSEN_NODE_DIR"] = self.node_dir
        cmd = action["cmd"]
        # The facade's wrap_cd bakes `cd <dir>; ` (dir defaults to "/",
        # control.clj *dir*) into the command before the remote sees
        # it. Rebase that exact prefix into the sandbox, so cwd-relative
        # suites stay contained.
        from .core import escape
        d = (context or {}).get("dir")
        if d:
            prefix = f"cd {escape(d)}; "
            if cmd.startswith(prefix):
                cmd = (f"cd {escape(self._rebase(d))}; "
                       + cmd[len(prefix):])
        try:
            proc = subprocess.run(
                ["bash", "-c", cmd],
                input=action.get("in"),
                capture_output=True, text=True,
                cwd=self.node_dir, env=env,
                timeout=action.get("timeout", self.timeout_s))
            return {**action, "exit": proc.returncode,
                    "out": proc.stdout, "err": proc.stderr}
        except subprocess.TimeoutExpired as e:
            return {**action, "exit": 124,
                    "out": (e.stdout or b"").decode()
                    if isinstance(e.stdout, bytes) else (e.stdout or ""),
                    "err": f"timed out after {self.timeout_s}s"}

    # -- file transfer -----------------------------------------------------

    def upload(self, context, local_paths, remote_path, opts=None):
        if isinstance(local_paths, (str, os.PathLike)):
            local_paths = [local_paths]
        dest = self._rebase(remote_path)
        many = len(local_paths) > 1 or os.path.isdir(dest)
        os.makedirs(dest if many else os.path.dirname(dest) or ".",
                    exist_ok=True)
        for lp in local_paths:
            target = os.path.join(dest, os.path.basename(lp)) if many \
                else dest
            if os.path.isdir(lp):
                shutil.copytree(lp, target, dirs_exist_ok=True)
            else:
                shutil.copy2(lp, target)

    def download(self, context, remote_paths, local_path, opts=None):
        if isinstance(remote_paths, (str, os.PathLike)):
            remote_paths = [remote_paths]
        many = len(remote_paths) > 1 or os.path.isdir(local_path)
        if many:
            os.makedirs(local_path, exist_ok=True)
        for rp in remote_paths:
            src = self._rebase(rp)
            if not os.path.exists(src):
                continue
            target = os.path.join(local_path, os.path.basename(rp)) \
                if many else local_path
            if os.path.isdir(src):
                shutil.copytree(src, target, dirs_exist_ok=True)
            else:
                os.makedirs(os.path.dirname(target) or ".",
                            exist_ok=True)
                shutil.copy2(src, target)


def remote(root: str, timeout_s: float = DEFAULT_TIMEOUT_S
           ) -> LocalExecRemote:
    return LocalExecRemote(root, timeout_s)
