"""Dependency graphs over transactions, as index arrays.

Nodes are history indices of completed transactions; edges are three
parallel int32 columns (src, dst, type). That struct-of-arrays layout is
deliberate: a future TPU pass can lift the columns straight into device
tensors (adjacency as a sparse matrix; SCC by repeated-squaring
reachability or forward/backward reach), while the host algorithms here
(iterative Tarjan SCC, BFS shortest cycle) serve as the oracle.

Graph construction parity targets: Elle's realtime graph (ops linked
when one completes before another begins — the strict-serializability
edge source) and process graph (per-process order), which the reference
passes as `:additional-graphs` (tests/cycle/append.clj:49-50,
tests/cycle/wr.clj:16-19).
"""

from __future__ import annotations

from collections import defaultdict, deque
from typing import Any, Callable, Iterable, Optional

import numpy as np

# Edge types
WW = 0        # write -> write (version order)
WR = 1        # write -> read  (information flow)
RW = 2        # read  -> write (anti-dependency)
REALTIME = 3  # completes-before-begins
PROCESS = 4   # same-process order

EDGE_NAMES = {WW: "ww", WR: "wr", RW: "rw", REALTIME: "realtime",
              PROCESS: "process"}


class DepGraph:
    """A typed digraph over txn indices, storable as index tensors."""

    def __init__(self):
        self._src: list[int] = []
        self._dst: list[int] = []
        self._typ: list[int] = []
        self._nodes: set[int] = set()
        # (src, dst, typ) -> arbitrary explanation payload
        self.labels: dict = {}

    def add_node(self, n: int) -> None:
        self._nodes.add(int(n))

    def add_edge(self, src: int, dst: int, typ: int,
                 label: Any = None) -> None:
        """Add src -> dst. Self-edges are dropped: a txn never depends
        on itself in Adya's formalism (internal anomalies are checked
        separately)."""
        src, dst = int(src), int(dst)
        if src == dst:
            return
        key = (src, dst, typ)
        if key in self.labels:
            return
        self.labels[key] = label
        self._src.append(src)
        self._dst.append(dst)
        self._typ.append(typ)
        self._nodes.add(src)
        self._nodes.add(dst)

    def merge(self, other: "DepGraph") -> "DepGraph":
        for (s, d, t), lab in other.labels.items():
            self.add_edge(s, d, t, lab)
        self._nodes |= other._nodes
        return self

    # -- tensor views --------------------------------------------------
    @property
    def edges(self) -> np.ndarray:
        """(E, 3) int32 array of (src, dst, type) — the TPU layout."""
        if not self._src:
            return np.zeros((0, 3), np.int32)
        return np.stack([np.asarray(self._src, np.int32),
                         np.asarray(self._dst, np.int32),
                         np.asarray(self._typ, np.int32)], axis=1)

    @property
    def nodes(self) -> np.ndarray:
        return np.asarray(sorted(self._nodes), np.int32)

    def __len__(self) -> int:
        return len(self._src)

    # -- host algorithms ----------------------------------------------
    def adjacency(self, types: Optional[set] = None) -> dict:
        adj: dict = defaultdict(list)
        for s, d, t in zip(self._src, self._dst, self._typ):
            if types is None or t in types:
                adj[s].append((d, t))
        return adj

    def sccs(self, types: Optional[set] = None) -> list[list[int]]:
        """Strongly connected components with >1 node, over the subgraph
        of the given edge types. Iterative Tarjan."""
        adj = self.adjacency(types)
        index: dict = {}
        low: dict = {}
        on_stack: set = set()
        stack: list = []
        sccs: list = []
        counter = [0]

        for root in sorted(self._nodes):
            if root in index:
                continue
            # iterative DFS: (node, iterator state)
            work = [(root, iter(adj.get(root, ())))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for child, _t in it:
                    if child not in index:
                        index[child] = low[child] = counter[0]
                        counter[0] += 1
                        stack.append(child)
                        on_stack.add(child)
                        work.append((child, iter(adj.get(child, ()))))
                        advanced = True
                        break
                    if child in on_stack:
                        low[node] = min(low[node], index[child])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        x = stack.pop()
                        on_stack.discard(x)
                        comp.append(x)
                        if x == node:
                            break
                    if len(comp) > 1:
                        sccs.append(sorted(comp))
        return sccs

    def find_cycle(self, types: Optional[set] = None) -> Optional[list]:
        """A shortest cycle in the subgraph of the given types, as a
        node list [a, b, ..., a]; None if acyclic."""
        for comp in self.sccs(types):
            cyc = self._cycle_in(set(comp), types)
            if cyc:
                return cyc
        return None

    def find_cycle_with(self, must_type: int, other_types: set,
                        exactly_one: bool = False) -> Optional[list]:
        """A cycle containing >=1 edge of must_type; with exactly_one,
        the remaining edges avoid must_type (Elle's G-single search: one
        rw edge closed by a ww/wr path)."""
        allowed = other_types | {must_type}
        adj = self.adjacency(other_types if exactly_one else allowed)
        for s, d, t in zip(self._src, self._dst, self._typ):
            if t != must_type:
                continue
            # path dst -> src closes the cycle around this edge
            path = _bfs_path(adj, d, s)
            if path is not None:
                return [s] + path  # [s, d, ..., s]
        return None

    def _cycle_in(self, comp: set, types: Optional[set]) -> Optional[list]:
        adj = self.adjacency(types)
        start = min(comp)
        # BFS back to start constrained to the component
        for nxt, _t in adj.get(start, ()):
            if nxt not in comp:
                continue
            if nxt == start:
                continue
            path = _bfs_path(adj, nxt, start, within=comp)
            if path is not None:
                return [start] + path
        return None

    def edge_type(self, src: int, dst: int) -> Optional[int]:
        """The 'strongest' edge type between src->dst (ww < wr < rw in
        explanation preference)."""
        best = None
        for (s, d, t) in self.labels:
            if s == src and d == dst and (best is None or t < best):
                best = t
        return best

    def explain_cycle(self, cycle: list) -> list[dict]:
        """Edge-by-edge explanation of a node cycle."""
        out = []
        for a, b in zip(cycle, cycle[1:]):
            t = self.edge_type(a, b)
            out.append({"from": a, "to": b,
                        "type": EDGE_NAMES.get(t, t),
                        "detail": self.labels.get((a, b, t))})
        return out


def _bfs_path(adj: dict, start: int, goal: int,
              within: Optional[set] = None) -> Optional[list]:
    """Shortest path start -> goal (inclusive); None if unreachable."""
    if start == goal:
        return [start]
    prev: dict = {start: None}
    q = deque([start])
    while q:
        node = q.popleft()
        for child, _t in adj.get(node, ()):
            if child in prev or (within is not None and child not in within):
                continue
            prev[child] = node
            if child == goal:
                path = [child]
                while prev[path[-1]] is not None:
                    path.append(prev[path[-1]])
                return path[::-1]
            q.append(child)
    return None


# -- additional graphs (Elle's :additional-graphs) -------------------------

def realtime_graph(history) -> DepGraph:
    """A completes strictly before B begins => A -> B, transitively
    reduced.

    Sweep events in time order keeping a frontier of completed ops not
    yet *superseded*. B invoking links B from every frontier op; those
    predecessors leave the frontier only when B COMPLETES — any op D
    invoking after B's completion reaches them through B (A -> B -> D),
    but an op C invoking before B completes still needs its own A -> C
    edge (removing predecessors at B's invocation would drop it)."""
    g = DepGraph()
    pairs = [(inv, comp) for inv, comp in history.pairs()
             if comp is not None and comp.is_ok]
    # events: (time, kind, ...); completions before invocations at equal
    # times (an op invoked at t sees completions at t)
    events = []
    for inv, comp in pairs:
        events.append((inv.time, 1, comp.index, inv, comp))
        events.append((comp.time, 0, comp.index, inv, comp))
    events.sort(key=lambda e: (e[0], e[1]))
    frontier: set = set()   # completed, not superseded
    done: dict = {}         # index -> completion op
    preds_of: dict = {}     # index -> frontier snapshot at invocation
    for _t, kind, idx, inv, comp in events:
        if kind == 1:
            preds = frontier - {idx}
            preds_of[idx] = preds
            for p in preds:
                g.add_edge(p, idx, REALTIME,
                           {"pred_completed": done[p].time,
                            "succ_began": inv.time})
        else:
            frontier -= preds_of.get(idx, set())
            frontier.add(idx)
            done[idx] = comp
    return g


def process_graph(history) -> DepGraph:
    """Consecutive completed ops of the same process => earlier ->
    later."""
    g = DepGraph()
    last: dict = {}
    for inv, comp in history.pairs():
        if comp is None or not comp.is_ok:
            continue
        p = inv.process
        if p in last:
            g.add_edge(last[p], comp.index, PROCESS, {"process": p})
        last[p] = comp.index
    return g
