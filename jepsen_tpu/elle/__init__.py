"""Transactional anomaly detection by dependency-graph cycle search.

This package is the framework's second compute plane: the capability of
the Elle checker (an external dependency of the reference, wrapped at
`jepsen/src/jepsen/tests/cycle.clj:9-16`,
`tests/cycle/append.clj:11-22`, and `tests/cycle/wr.clj:14-53`),
re-implemented from its published semantics rather than ported:

  * `jepsen_tpu.elle.graph`   — dependency graphs held as index arrays
                                (src/dst/type int32 columns — the layout
                                a TPU SCC pass consumes directly), with
                                host Tarjan SCC + shortest-cycle search;
  * `jepsen_tpu.elle.append`  — list-append histories: infer the version
                                order of each key's list from observed
                                read prefixes, derive ww/wr/rw edges,
                                and classify G0/G1a/G1b/G1c/G-single/G2
                                plus internal/dirty-update/duplicate/
                                incompatible-order anomalies;
  * `jepsen_tpu.elle.wr`      — write/read registers with unique writes:
                                version orders inferred under the
                                sequential/linearizable/wfr assumptions;
  * `jepsen_tpu.elle.build`   — tensorized graph construction: flat
                                micro-op columns in, (E, 3) edge columns
                                + interval-jump metadata out, no
                                DepGraph on the hot path;
  * `jepsen_tpu.elle.tpu`     — the device cycle-query battery (bf16 /
                                bitset-packed squaring, peel-to-core
                                trim) behind shape-aware auto-routing.

Anomaly taxonomy (naming follows Adya, as the reference documents in
tests/cycle/wr.clj:30-46):

  G0        write cycle (ww edges only)
  G1a       aborted read
  G1b       intermediate read
  G1c       circular information flow (ww + wr edges)
  G-single  cycle with exactly one anti-dependency (rw) edge
  G2        cycle with at least one rw edge
  internal  txn inconsistent with its own prior reads/writes
"""

from .graph import (EDGE_NAMES, PROCESS, REALTIME, RW, WR, WW, DepGraph,
                    process_graph, realtime_graph)

__all__ = ["DepGraph", "WW", "WR", "RW", "REALTIME", "PROCESS",
           "EDGE_NAMES", "realtime_graph", "process_graph"]
