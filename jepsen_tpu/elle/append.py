"""List-append anomaly detection.

Histories of transactions over named lists, where each mop either
appends a unique value to a key's list or reads the key's whole list:

    {"type": "ok", "f": "txn",
     "value": [["append", 3, 2], ["r", 3, [1, 2]]]}

Because appends are unique and reads return *whole* lists, each read is
a trace of the key's version history: the observed list IS the order in
which appends committed. That recoverability is what makes list-append
the strongest workload in the reference's arsenal (wrapped at
`jepsen/src/jepsen/tests/cycle/append.clj:11-55`; the engine is the
external Elle library, re-implemented here from its semantics).

Pipeline:
  1. validate reads (duplicates, incompatible prefixes) and recover each
     key's version order (the longest observed prefix chain);
  2. direct anomalies: internal (txn vs its own prior ops), G1a (read of
     a failed txn's append), G1b (read of an intermediate append),
     dirty-update (failed append observed in version order);
  3. dependency graph: ww (consecutive appends in version order), wr
     (append observed as the read's last element), rw (read's
     last-observed element -> writer of the next version), plus optional
     realtime/process graphs;
  4. cycle classification over the typed graph: G0 (ww only), G1c
     (ww+wr), G-single (exactly one rw), G2 (>=1 rw).
"""

from __future__ import annotations

import random
from typing import Any, Iterable, Optional

from ..history import History
from ..txn import APPEND, R
from .graph import (EDGE_NAMES, PROCESS, REALTIME, RW, WR, WW, DepGraph,
                    process_graph, realtime_graph)

# anomaly -> weakest consistency model it violates (Elle's :not field)
MODEL_VIOLATIONS = {
    "G0": "read-uncommitted",
    "G1a": "read-committed",
    "G1b": "read-committed",
    "G1c": "read-committed",
    "G-single": "consistent-view",
    "G2": "serializable",
    "internal": "read-atomic",
    "dirty-update": "read-committed",
    "duplicate-elements": "serializable",
    "incompatible-order": "serializable",
    "cyclic-versions": "read-uncommitted",
}

DEFAULT_ANOMALIES = ("G0", "G1a", "G1b", "G1c", "G-single", "G2",
                     "internal", "dirty-update", "duplicate-elements",
                     "incompatible-order")


def check(history: History, anomalies: Iterable[str] = DEFAULT_ANOMALIES,
          additional_graphs: Iterable[str] = (),
          cycle_backend: str = "auto") -> dict:
    """Analyze a list-append history. Returns
    {"valid?": bool, "anomaly-types": [...], "anomalies": {...},
    "not": [violated models]}.

    cycle_backend: "host" (Tarjan oracle), "tpu" / "packed" / "prop"
    / "device" (the elle/tpu.py kernel family), or "auto"
    (shape-routed via ops/route.elle_cycle_route)."""
    import time as _time

    from ..analysis import history_lint
    bad = history_lint.gate(history, where="elle.append",
                            rules=history_lint.ELLE_GATE_RULES)
    if bad is not None:
        # malformed input: version-order inference over a corrupted
        # event order would fabricate anomalies — fast-fail instead
        return {"valid?": "unknown",
                "anomaly-types": ["malformed-history"],
                "anomalies": {"malformed-history": bad["anomalies"]},
                "not": [], "analyzer": bad["analyzer"]}
    t_start = _time.monotonic()
    anomalies = set(anomalies)
    found: dict[str, list] = {}
    for name in additional_graphs:
        if name not in ("realtime", "process"):
            raise ValueError(f"unknown additional graph {name!r}")

    completed = [op for op in history
                 if op.type in ("ok", "info") and op.f in ("txn", None)
                 and op.value]
    oks = [op for op in completed if op.is_ok]
    infos = [op for op in completed if op.is_info]
    failed = [op for op in history if op.is_fail and op.value]

    # Admission preflight (analysis/preflight): a dense-closure
    # request whose graph can never fit the device (P001/P002 —
    # e.g. a forced cycle_backend="packed" at 100k txns) is rejected
    # HERE, before the graph build, any backend compile, or any
    # device byte — the static twin of the capacity checks the
    # kernels only discover by refusing at runtime.
    if cycle_backend != "host":
        from ..analysis import preflight
        bad_pf = preflight.gate_elle(len(completed),
                                     backend=cycle_backend,
                                     where="elle.append")
        if bad_pf is not None:
            return {"valid?": "unknown",
                    "anomaly-types": ["preflight"],
                    "anomalies": {"preflight": [bad_pf["preflight"]]},
                    "not": [], "preflight": bad_pf["preflight"]}

    # -- 1. tensorized construction (elle/build.py): writer index,
    #    version orders, and the ww/wr/rw(+rt/proc) edge columns come
    #    out of one vectorized pass; dirty histories fall back to the
    #    exact host loops inside the builder ---------------------------
    from . import build as build_mod
    try:
        bt = build_mod.build_append(history, oks, infos,
                                    additional_graphs=additional_graphs)
        writer, orders = bt.writer, bt.orders
        dup_anoms, order_anoms = bt.dup_anomalies, bt.order_anomalies
        gt = bt.tensors
        gt._explain = lambda: _legacy_graph(history, orders, writer,
                                            oks, additional_graphs)
        _record_build("append", bt)
    except build_mod.BuildUnsupported:
        writer, dup_anoms = _writer_index(oks, infos)
        orders, order_anoms = _version_orders(oks)
        gt = _legacy_graph(history, orders, writer, oks,
                           additional_graphs)
    if dup_anoms:
        found["duplicate-elements"] = dup_anoms
    if order_anoms:
        found["incompatible-order"] = order_anoms

    # -- 2. direct anomalies ---------------------------------------------
    internal = _internal_cases(oks)
    if internal:
        found["internal"] = internal
    g1a = _g1a_cases(oks, failed)
    if g1a:
        found["G1a"] = g1a
    g1b = _g1b_cases(oks)
    if g1b:
        found["G1b"] = g1b
    dirty = _dirty_update_cases(orders, writer)
    if dirty:
        found["dirty-update"] = dirty

    # -- 3+4. cycles over the edge columns -------------------------------
    from .tpu import standard_cycle_search
    cycles = standard_cycle_search(gt, backend=cycle_backend)
    g = None  # the labeled DepGraph materializes only to EXPLAIN
    if any(cycles[q] for q in ("G0", "G1c", "G-single", "G2")):
        g = gt.to_depgraph() if hasattr(gt, "to_depgraph") else gt
    if cycles["G0"]:
        found["G0"] = [_cycle_case(g, cycles["G0"], history)]
    if cycles["G1c"] and "G0" not in found:
        found["G1c"] = [_cycle_case(g, cycles["G1c"], history)]
    if cycles["G-single"]:
        found["G-single"] = [_cycle_case(g, cycles["G-single"], history)]
    if cycles["G2"] and "G-single" not in found:
        found["G2"] = [_cycle_case(g, cycles["G2"], history)]

    reported = {k: v for k, v in found.items() if k in anomalies}
    # anomalies outside the requested set still make the result unknown
    silent = set(found) - set(reported)
    valid: Any = not reported
    if valid and silent:
        valid = "unknown"
    out = {"valid?": valid,
           "anomaly-types": sorted(reported),
           "anomalies": reported,
           "cycle-engine": cycles.get("engine"),
           "not": sorted({MODEL_VIOLATIONS[a] for a in reported
                          if a in MODEL_VIOLATIONS})}
    if cycles.get("util"):
        out["cycle-util"] = cycles["util"]
    if cycles.get("route_reason"):
        out["cycle-route-reason"] = cycles["route_reason"]
    if silent:
        out["unchecked-anomaly-types"] = sorted(silent)
    _record_elle("elle.append", out, len(oks),
                 _time.monotonic() - t_start)
    return out


def _legacy_graph(history, orders, writer, oks, additional_graphs):
    """The host-builder graph: the oracle/explanation side of the
    tensorized pass, and the whole pipeline when tensorization is
    unsupported."""
    g = graph(history, orders=orders, writer=writer, oks=oks)
    for name in additional_graphs:
        if name == "realtime":
            g.merge(realtime_graph(history))
        elif name == "process":
            g.merge(process_graph(history))
    return g


def _record_build(checker: str, bt) -> None:
    """elle_build series: one point per tensorized construction."""
    from .. import metrics as _metrics
    mx = _metrics.get_default()
    if not mx.enabled:
        return
    mx.series("elle_build",
              "tensorized elle graph construction").append(
        {"checker": checker, "txns": int(len(bt.tensors.nodes)),
         "mops": int(bt.micro_ops), "edges": len(bt.tensors),
         "edge_counts": bt.tensors.counts(),
         "build_s": round(bt.tensors.build_s, 4),
         "builder": bt.builder})


def _record_elle(name: str, out: dict, op_count: int,
                 wall_s: float) -> None:
    """Run-ledger record (kind="elle") — device-seconds ride
    util.kernel_s via ledger.device_seconds, so /runs aggregates and
    regressions() cover the elle family next to WGL."""
    from .. import ledger as _ledger
    from ..util import safe_backend
    res = {"valid?": out.get("valid?"),
           "cause": ",".join(out.get("anomaly-types") or []) or None,
           "op_count": op_count,
           "engine": out.get("cycle-engine"),
           "util": out.get("cycle-util")}
    _ledger.record_result("elle", name, res, wall_s=wall_s,
                          engine=out.get("cycle-engine"),
                          platform=safe_backend())


def graph(history: History, orders: Optional[dict] = None,
          writer: Optional[dict] = None,
          oks: Optional[list] = None) -> DepGraph:
    """The ww/wr/rw dependency graph of a list-append history."""
    if oks is None:
        oks = [op for op in history
               if op.is_ok and op.f in ("txn", None) and op.value]
    if writer is None:
        writer, _ = _writer_index(oks, [])
    if orders is None:
        orders, _ = _version_orders(oks)

    g = DepGraph()
    for op in oks:
        g.add_node(op.index)

    # ww: consecutive appends in each key's version order
    for k, order in orders.items():
        for v1, v2 in zip(order, order[1:]):
            w1, w2 = writer.get((k, v1)), writer.get((k, v2))
            if w1 is not None and w2 is not None:
                g.add_edge(w1, w2, WW,
                           {"key": k, "value": v1, "next_value": v2})

    # wr and rw from each external read
    for op in oks:
        own_appends = {(k, v) for f, k, v in op.value if f == APPEND}
        for f, k, v in op.value:
            if f != R or v is None:
                continue
            observed = [x for x in v if (k, x) not in own_appends]
            if observed:
                last = observed[-1]
                w = writer.get((k, last))
                if w is not None:
                    g.add_edge(w, op.index, WR,
                               {"key": k, "value": last})
            # rw: the next version after what we observed
            order = orders.get(k, [])
            prefix_len = len(v)
            if prefix_len < len(order):
                nxt = order[prefix_len]
                w = writer.get((k, nxt))
                if w is not None:
                    g.add_edge(op.index, w, RW,
                               {"key": k, "observed": list(v),
                                "next_value": nxt})
    return g


# -- internals ---------------------------------------------------------------

def _writer_index(oks, infos):
    """(k, v) -> writer op index over ok + info appends (info writes MAY
    have happened, so they participate in the graph), plus
    duplicate-append anomalies."""
    writer: dict = {}
    dups: list = []
    for op in list(oks) + list(infos):
        for f, k, v in op.value or []:
            if f != APPEND:
                continue
            if (k, v) in writer and writer[(k, v)] != op.index:
                dups.append({"key": k, "value": v,
                             "writers": [writer[(k, v)], op.index],
                             "explanation":
                             f"value {v!r} appended to key {k!r} by "
                             f"two different transactions"})
            writer[(k, v)] = op.index
    return writer, dups


def _version_orders(oks):
    """key -> list of values in version order, from observed reads.
    Every read must be a prefix of the longest read; mismatches are
    incompatible-order anomalies."""
    longest: dict = {}
    anoms: list = []
    for op in oks:
        for f, k, v in op.value:
            if f != R or v is None:
                continue
            cur = longest.get(k, [])
            short, long_ = (v, cur) if len(v) <= len(cur) else (cur, v)
            if list(short) != list(long_[:len(short)]):
                anoms.append({"key": k, "a": list(cur), "b": list(v),
                              "explanation":
                              f"reads of key {k!r} observed "
                              f"incompatible orders {cur!r} and {v!r}"})
            elif len(v) > len(cur):
                longest[k] = list(v)
    return longest, anoms


def _internal_cases(oks):
    """Reads inconsistent with the txn's own prior mops
    (read-atomic violations within a single txn)."""
    cases = []
    for op in oks:
        # expected[k] = (base_list_or_None, own_appends)
        state: dict = {}
        for mi, (f, k, v) in enumerate(op.value):
            if f == APPEND:
                base, own = state.get(k, (None, []))
                state[k] = (base, own + [v])
            elif f == R and v is not None:
                base, own = state.get(k, (None, []))
                if base is None and not own:
                    state[k] = (list(v), [])
                    continue
                if base is None:
                    # first read after own appends: list must end with
                    # exactly our appends, in order
                    if list(v[len(v) - len(own):]) != own:
                        cases.append(_internal_case(op, mi, k, v, own))
                    else:
                        state[k] = (list(v[:len(v) - len(own)]), own)
                else:
                    if list(v) != base + own:
                        cases.append(_internal_case(op, mi, k, v,
                                                    base + own))
    return cases


def _internal_case(op, mi, k, v, expected):
    return {"op-index": op.index, "mop-index": mi, "key": k,
            "observed": list(v), "expected": list(expected),
            "explanation":
            f"txn at index {op.index} read {list(v)!r} from key {k!r}, "
            f"inconsistent with its own prior operations "
            f"(expected suffix/state {expected!r})"}


def _g1a_cases(oks, failed):
    """Reads observing a value appended by a *failed* txn."""
    failed_writes = {}
    for op in failed:
        for f, k, v in op.value or []:
            if f == APPEND:
                failed_writes[(k, v)] = op.index
    cases = []
    for op in oks:
        for f, k, v in op.value:
            if f != R or v is None:
                continue
            for x in v:
                if (k, x) in failed_writes:
                    cases.append({
                        "op-index": op.index, "key": k, "value": x,
                        "writer-index": failed_writes[(k, x)],
                        "explanation":
                        f"txn at index {op.index} observed value {x!r} "
                        f"of key {k!r}, which was appended by FAILED "
                        f"txn at index {failed_writes[(k, x)]}"})
    return cases


def _g1b_cases(oks):
    """Reads whose final element is an *intermediate* append: the
    writer went on to append more to that key in the same txn."""
    from ..txn import int_write_mops
    # (k, v) -> writer index when v is a non-final append of its txn
    intermediate = {}
    for op in oks:
        for k, mops in int_write_mops(op.value).items():
            for m in mops:
                intermediate[(k, m[2])] = op.index
    cases = []
    for op in oks:
        own = {(k, v) for f, k, v in op.value if f == APPEND}
        for f, k, v in op.value:
            if f != R or not v:
                continue
            last = v[-1]
            if (k, last) in intermediate and (k, last) not in own \
                    and intermediate[(k, last)] != op.index:
                cases.append({
                    "op-index": op.index, "key": k, "value": last,
                    "writer-index": intermediate[(k, last)],
                    "explanation":
                    f"txn at index {op.index} read key {k!r} up to "
                    f"value {last!r}, an intermediate append of txn "
                    f"at index {intermediate[(k, last)]}"})
    return cases


def _dirty_update_cases(orders, writer):
    """A failed/aborted append that nonetheless shows up in the middle
    of a version order was 'resurrected' by later committed appends.
    (With the writer index built from ok+info ops only, a version-order
    element with no writer is a failed write that readers observed.)"""
    # G1a already reports observed-failed-values; dirty-update in Elle
    # is about a committed write overwriting an aborted one. For
    # list-append, every later append "overwrites" (extends) earlier
    # ones, so any failed append INSIDE a version order qualifies.
    cases = []
    for k, order in orders.items():
        for i, v in enumerate(order[:-1]):  # not the last: must be built on
            if (k, v) not in writer:
                cases.append({
                    "key": k, "value": v, "position": i,
                    "explanation":
                    f"key {k!r} version order contains value {v!r} with "
                    f"no committed writer, yet later appends built on "
                    f"top of it"})
    return cases


def _cycle_case(g: DepGraph, cycle: list, history: History) -> dict:
    steps = g.explain_cycle(cycle)
    lines = []
    for s in steps:
        det = s["detail"] or {}
        if s["type"] == "ww":
            lines.append(f"T{s['from']} appended {det.get('value')!r} to "
                         f"key {det.get('key')!r} before T{s['to']} "
                         f"appended {det.get('next_value')!r}")
        elif s["type"] == "wr":
            lines.append(f"T{s['to']} read value {det.get('value')!r} of "
                         f"key {det.get('key')!r} appended by "
                         f"T{s['from']}")
        elif s["type"] == "rw":
            lines.append(f"T{s['from']} observed key {det.get('key')!r} "
                         f"as {det.get('observed')!r} before T{s['to']} "
                         f"appended {det.get('next_value')!r}")
        else:
            lines.append(f"T{s['from']} -> T{s['to']} ({s['type']})")
    return {"cycle": cycle, "steps": steps, "explanation": "; ".join(lines)}


# -- generator ---------------------------------------------------------------

class AppendGen:
    """Generates list-append transactions (elle.list-append/gen
    semantics, exposed at tests/cycle/append.clj:28-31): a rotating pool
    of active keys, unique monotonically increasing write values per
    key, keys retired after max_writes_per_key writes. The write mop
    tag is parameterizable so the rw-register generator (unique plain
    writes) shares the exact same key-pool behavior."""

    write_f = APPEND

    def __init__(self, key_count: int = 3, min_txn_length: int = 1,
                 max_txn_length: int = 4, max_writes_per_key: int = 32,
                 seed: Optional[int] = None):
        self.key_count = key_count
        self.min_len = min_txn_length
        self.max_len = max_txn_length
        self.max_writes = max_writes_per_key
        self.rng = random.Random(seed)
        self.next_key = key_count
        self.active = list(range(key_count))
        self.writes: dict = {k: 0 for k in self.active}

    def txn(self) -> list:
        n = self.rng.randint(self.min_len, self.max_len)
        out = []
        for _ in range(n):
            k = self.rng.choice(self.active)
            if self.rng.random() < 0.5:
                out.append([R, k, None])
            else:
                self.writes[k] += 1
                out.append([self.write_f, k, self.writes[k]])
                if self.writes[k] >= self.max_writes:
                    self.active.remove(k)
                    self.active.append(self.next_key)
                    self.writes[self.next_key] = 0
                    self.next_key += 1
        return out

    def __call__(self, test, ctx):
        """As a function generator for the DSL: emits txn invocations
        forever."""
        return {"f": "txn", "value": self.txn()}
