"""Write/read register anomaly detection.

Histories of transactions over registers where every write is unique:

    {"type": "ok", "f": "txn", "value": [["w", "x", 1], ["r", "x", 1]]}

Unlike list-append, a register read reveals only the *current* value,
not the version history — so version orders must be inferred under
explicit assumptions, exactly the knobs the reference exposes
(`jepsen/src/jepsen/tests/cycle/wr.clj:14-53`):

    sequential_keys    each key is sequentially consistent; derive
                       version order from per-process write/read order
    linearizable_keys  each key is linearizable; derive version order
                       from realtime order
    wfr_keys           within a txn, writes follow reads: read of v
                       then write of v' on the same key => v < v'

From whatever version-order fragments those sources give (plus "the
initial nil state precedes everything"), we build a per-key version
graph; a cyclic version graph is itself an anomaly (cyclic-versions),
an acyclic one is linearized topologically and the ww/wr/rw txn graph
follows as in list-append. Direct anomalies (G1a aborted read, G1b
intermediate read, internal) don't need version orders at all.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Iterable, Optional

from ..history import History
from ..txn import R, W
from .graph import (PROCESS, REALTIME, RW, WR, WW, DepGraph,
                    process_graph, realtime_graph)
from .append import MODEL_VIOLATIONS, AppendGen

DEFAULT_ANOMALIES = ("G0", "G1a", "G1b", "G1c", "G-single", "G2",
                     "internal", "cyclic-versions")

INIT = object()  # the initial (unwritten, nil) version of every key


def check(history: History, anomalies: Iterable[str] = DEFAULT_ANOMALIES,
          additional_graphs: Iterable[str] = (),
          sequential_keys: bool = False,
          linearizable_keys: bool = False,
          wfr_keys: bool = False,
          cycle_backend: str = "auto") -> dict:
    """Analyze a write/read register history. cycle_backend as in
    append.check: "host" | "tpu" | "packed" | "prop" | "device" |
    "auto"."""
    import time as _time

    from ..analysis import history_lint
    bad = history_lint.gate(history, where="elle.wr",
                            rules=history_lint.ELLE_GATE_RULES)
    if bad is not None:
        return {"valid?": "unknown",
                "anomaly-types": ["malformed-history"],
                "anomalies": {"malformed-history": bad["anomalies"]},
                "not": [], "analyzer": bad["analyzer"]}
    t_start = _time.monotonic()
    anomalies = set(anomalies)
    found: dict[str, list] = {}
    for name in additional_graphs:
        if name not in ("realtime", "process"):
            raise ValueError(f"unknown additional graph {name!r}")

    oks = [op for op in history
           if op.is_ok and op.f in ("txn", None) and op.value]
    infos = [op for op in history
             if op.is_info and op.f in ("txn", None) and op.value]
    failed = [op for op in history if op.is_fail and op.value]

    # Admission preflight (analysis/preflight): reject a device
    # closure request over kernel capacity / HBM budget (P001/P002)
    # before the graph build — see elle/append.py.
    if cycle_backend != "host":
        from ..analysis import preflight
        bad_pf = preflight.gate_elle(len(oks) + len(infos),
                                     backend=cycle_backend,
                                     where="elle.wr")
        if bad_pf is not None:
            return {"valid?": "unknown",
                    "anomaly-types": ["preflight"],
                    "anomalies": {"preflight": [bad_pf["preflight"]]},
                    "not": [], "preflight": bad_pf["preflight"]}

    # tensorized construction (elle/build.py): writer index, version
    # evidence, and the edge columns in one vectorized pass
    from . import build as build_mod
    from .append import _record_build, _record_elle
    try:
        bt = build_mod.build_wr(history, oks, infos,
                                sequential_keys=sequential_keys,
                                linearizable_keys=linearizable_keys,
                                wfr_keys=wfr_keys,
                                additional_graphs=additional_graphs)
        writer, orders, cyclic = bt.writer, bt.orders, \
            bt.cyclic_anomalies
        gt = bt.tensors
        gt._explain = lambda: _legacy_graph(history, oks, writer,
                                            orders, additional_graphs)
        _record_build("wr", bt)
    except build_mod.BuildUnsupported:
        writer = _writer_index(oks + infos)
        orders, cyclic = _version_orders(
            history, oks, writer, sequential_keys=sequential_keys,
            linearizable_keys=linearizable_keys, wfr_keys=wfr_keys)
        gt = _legacy_graph(history, oks, writer, orders,
                           additional_graphs)

    internal = _internal_cases(oks)
    if internal:
        found["internal"] = internal
    g1a = _g1a_cases(oks, failed)
    if g1a:
        found["G1a"] = g1a
    g1b = _g1b_cases(oks)
    if g1b:
        found["G1b"] = g1b
    if cyclic:
        found["cyclic-versions"] = cyclic

    from .tpu import standard_cycle_search
    cycles = standard_cycle_search(gt, backend=cycle_backend)
    g = None  # the labeled DepGraph materializes only to EXPLAIN
    if any(cycles[q] for q in ("G0", "G1c", "G-single", "G2")):
        g = gt.to_depgraph() if hasattr(gt, "to_depgraph") else gt
    if cycles["G0"]:
        found["G0"] = [_cycle_case(g, cycles["G0"])]
    if cycles["G1c"] and "G0" not in found:
        found["G1c"] = [_cycle_case(g, cycles["G1c"])]
    if cycles["G-single"]:
        found["G-single"] = [_cycle_case(g, cycles["G-single"])]
    if cycles["G2"] and "G-single" not in found:
        found["G2"] = [_cycle_case(g, cycles["G2"])]

    reported = {k: v for k, v in found.items() if k in anomalies}
    silent = set(found) - set(reported)
    valid: Any = not reported
    if valid and silent:
        valid = "unknown"
    out = {"valid?": valid,
           "anomaly-types": sorted(reported),
           "anomalies": reported,
           "cycle-engine": cycles.get("engine"),
           "not": sorted({MODEL_VIOLATIONS[a] for a in reported
                          if a in MODEL_VIOLATIONS})}
    if cycles.get("util"):
        out["cycle-util"] = cycles["util"]
    if cycles.get("route_reason"):
        out["cycle-route-reason"] = cycles["route_reason"]
    if silent:
        out["unchecked-anomaly-types"] = sorted(silent)
    _record_elle("elle.wr", out, len(oks), _time.monotonic() - t_start)
    return out


def _legacy_graph(history, oks, writer, orders, additional_graphs):
    """The host-builder graph: the oracle/explanation side of the
    tensorized pass."""
    g = _txn_graph(oks, writer, orders)
    for name in additional_graphs:
        if name == "realtime":
            g.merge(realtime_graph(history))
        elif name == "process":
            g.merge(process_graph(history))
    return g


# -- internals ---------------------------------------------------------------

def _writer_index(ops):
    """(k, v) -> op index for every write (unique-writes assumption)."""
    writer: dict = {}
    for op in ops:
        for f, k, v in op.value or []:
            if f == W:
                writer[(k, v)] = op.index
    return writer


def _internal_cases(oks):
    cases = []
    for op in oks:
        state: dict = {}  # key -> last known value within the txn
        for mi, (f, k, v) in enumerate(op.value):
            if f == W:
                state[k] = v
            elif f == R:
                if k in state and state[k] != v:
                    cases.append({
                        "op-index": op.index, "mop-index": mi, "key": k,
                        "observed": v, "expected": state[k],
                        "explanation":
                        f"txn at index {op.index} read {v!r} from key "
                        f"{k!r} but its own prior state was "
                        f"{state[k]!r}"})
                else:
                    state[k] = v
    return cases


def _g1a_cases(oks, failed):
    failed_writes = {}
    for op in failed:
        for f, k, v in op.value or []:
            if f == W:
                failed_writes[(k, v)] = op.index
    cases = []
    for op in oks:
        for f, k, v in op.value:
            if f == R and (k, v) in failed_writes:
                cases.append({
                    "op-index": op.index, "key": k, "value": v,
                    "writer-index": failed_writes[(k, v)],
                    "explanation":
                    f"txn at index {op.index} observed value {v!r} of "
                    f"key {k!r}, written by FAILED txn at index "
                    f"{failed_writes[(k, v)]}"})
    return cases


def _g1b_cases(oks):
    from ..txn import int_write_mops
    intermediate = {}
    for op in oks:
        for k, mops in int_write_mops(op.value).items():
            for m in mops:
                intermediate[(k, m[2])] = op.index
    cases = []
    for op in oks:
        for f, k, v in op.value:
            if f == R and (k, v) in intermediate \
                    and intermediate[(k, v)] != op.index:
                cases.append({
                    "op-index": op.index, "key": k, "value": v,
                    "writer-index": intermediate[(k, v)],
                    "explanation":
                    f"txn at index {op.index} read {v!r} of key {k!r}, "
                    f"an intermediate write of txn at index "
                    f"{intermediate[(k, v)]}"})
    return cases


def _version_orders(history, oks, writer, sequential_keys=False,
                    linearizable_keys=False, wfr_keys=False):
    """Per-key version *evidence graph*: k -> {v1: set of v2 directly
    after v1}.

    Only evidenced precedence is recorded — we never linearize the
    partial order into an arbitrary total one, because txn edges
    derived from a fabricated order would report anomalies the history
    doesn't actually exhibit. Sources of v1 < v2 evidence on key k:

      * INIT precedes every written value (unconditional);
      * wfr_keys: a txn reads v1 then writes v2 on k;
      * sequential_keys: per-process order of reads/writes of k;
      * linearizable_keys: realtime order — evidence only between ops
        where one COMPLETES before the other INVOKES (concurrent ops
        yield no evidence; using completion order alone would
        over-constrain and manufacture false cyclic-versions).

    Returns ({k: {v: {v'...}}}, cyclic_anomalies)."""
    prec: dict = defaultdict(set)  # k -> set of (v1, v2)

    for op in oks:
        last_read: dict = {}
        for f, k, v in op.value:
            if f == R:
                last_read[k] = v
            elif f == W:
                if wfr_keys and k in last_read and last_read[k] != v:
                    prec[k].add((INIT if last_read[k] is None
                                 else last_read[k], v))
                prec[k].add((INIT, v))

    def track_order(seq_of_ops):
        """Feed per-key observation sequences: consecutive distinct
        observed/written values imply version order (a nil read
        observes the INIT version)."""
        last: dict = {}
        for op in seq_of_ops:
            for f, k, v in op.value:
                if f == R:
                    cur = INIT if v is None else v
                elif f == W:
                    cur = v
                else:
                    continue
                prev = last.get(k)
                if prev is not None and prev != cur:
                    prec[k].add((prev, cur))
                last[k] = cur

    if sequential_keys:
        per_proc: dict = defaultdict(list)
        for op in oks:
            per_proc[op.process].append(op)
        for ops in per_proc.values():
            track_order(ops)
    if linearizable_keys:
        _realtime_evidence(history, prec)

    orders: dict = {}
    cyclic: list = []
    for k, pairs in prec.items():
        adj: dict = defaultdict(set)
        for a, b in pairs:
            adj[a].add(b)
        if _has_cycle(adj):
            cyclic.append({"key": k,
                           "explanation":
                           f"version precedence evidence for key {k!r} "
                           f"is cyclic: {_fmt_pairs(pairs)}"})
        else:
            orders[k] = {a: set(bs) for a, bs in adj.items()}
    return orders, cyclic


def _realtime_evidence(history, prec):
    """Evidence from realtime order: if op A completes strictly before
    op B invokes, A's final observation of k precedes B's first
    observation of k. Sweep by invocation time, remembering the
    latest-completed op's final value per key (an under-approximation
    for overlapping ops — sound, never over-constraining)."""
    pairs = [(inv, comp) for inv, comp in history.pairs()
             if comp is not None and comp.is_ok and comp.value]
    pairs.sort(key=lambda p: p[0].time)
    latest: dict = {}  # k -> (comp_time, final value)
    for inv, comp in pairs:
        first: dict = {}
        final: dict = {}
        for f, k, v in comp.value:
            if f == R:
                cur = INIT if v is None else v
            elif f == W:
                cur = v
            else:
                continue
            first.setdefault(k, cur)
            final[k] = cur
        for k, cur in first.items():
            if k in latest:
                t_prev, v_prev = latest[k]
                if t_prev < inv.time and v_prev != cur:
                    prec[k].add((v_prev, cur))
        for k, cur in final.items():
            if k not in latest or latest[k][0] < comp.time:
                latest[k] = (comp.time, cur)


def _has_cycle(adj) -> bool:
    """DFS cycle check over a {node: successors} graph."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color: dict = defaultdict(int)
    for start in list(adj):
        if color[start] != WHITE:
            continue
        stack = [(start, iter(adj.get(start, ())))]
        color[start] = GRAY
        while stack:
            node, it = stack[-1]
            advanced = False
            for child in it:
                if color[child] == GRAY:
                    return True
                if color[child] == WHITE:
                    color[child] = GRAY
                    stack.append((child, iter(adj.get(child, ()))))
                    advanced = True
                    break
            if not advanced:
                color[node] = BLACK
                stack.pop()
    return False


def _fmt_pairs(pairs):
    return sorted((("nil" if a is INIT else a, b) for a, b in pairs),
                  key=repr)


def _txn_graph(oks, writer, orders):
    """ww/wr/rw edges from the evidence graphs. `orders` maps
    k -> {v: direct evidenced successors of v}."""
    g = DepGraph()
    for op in oks:
        g.add_node(op.index)

    # ww: directly-evidenced version adjacency
    for k, succ in orders.items():
        for v1, nxts in succ.items():
            for v2 in nxts:
                w1, w2 = writer.get((k, v1)), writer.get((k, v2))
                if w1 is not None and w2 is not None:
                    g.add_edge(w1, w2, WW,
                               {"key": k, "value": v1, "next_value": v2})

    # wr + rw from external reads
    from ..txn import ext_reads
    for op in oks:
        for k, v in ext_reads(op.value).items():
            if v is not None:
                w = writer.get((k, v))
                if w is not None:
                    g.add_edge(w, op.index, WR, {"key": k, "value": v})
            succ = orders.get(k)
            if not succ:
                continue
            cur = v if v is not None else INIT
            for nxt in succ.get(cur, ()):
                w = writer.get((k, nxt))
                if w is not None:
                    g.add_edge(op.index, w, RW,
                               {"key": k, "observed": v,
                                "next_value": nxt})
    return g


def _cycle_case(g: DepGraph, cycle: list) -> dict:
    steps = g.explain_cycle(cycle)
    lines = []
    for s in steps:
        det = s["detail"] or {}
        if s["type"] == "ww":
            lines.append(f"T{s['from']} wrote {det.get('value')!r} to key "
                         f"{det.get('key')!r} before T{s['to']} wrote "
                         f"{det.get('next_value')!r}")
        elif s["type"] == "wr":
            lines.append(f"T{s['to']} read value {det.get('value')!r} of "
                         f"key {det.get('key')!r} written by T{s['from']}")
        elif s["type"] == "rw":
            lines.append(f"T{s['from']} observed {det.get('observed')!r} "
                         f"of key {det.get('key')!r} before T{s['to']} "
                         f"wrote {det.get('next_value')!r}")
        else:
            lines.append(f"T{s['from']} -> T{s['to']} ({s['type']})")
    return {"cycle": cycle, "steps": steps, "explanation": "; ".join(lines)}


# -- generator ---------------------------------------------------------------

class WrGen(AppendGen):
    """Register txn generator: identical key-pool behavior to
    AppendGen, but emits plain unique writes (rw-register's core
    assumption) instead of appends."""

    write_f = W
