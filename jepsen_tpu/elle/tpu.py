"""The TPU Elle plane: cycle detection as dense boolean linear algebra.

Three device kernels now share the query battery (routing below picks
per shape, ops/route.elle_cycle_route):

  bf16    dense (S, N, N) closure by repeated squaring on the MXU —
          the original kernel, validated on the 8-device mesh
          (MULTICHIP_r05), capacity 8k txns.
  packed  the same closure over uint32 bitset words: (S, N, N/32)
          storage (16x less than bf16), AND/OR-reduce squaring over
          32-column blocks, popcount occupancy counters. Bit-identical
          outputs to bf16 (tests/test_elle_tpu.py pins it), lifts the
          dense capacity cap to 32k txns; per shape bucket the
          bf16-vs-packed choice is made from Lowered.cost_analysis
          bytes (the ops/adapt.py packed-table pattern).
  trim    peel-to-core cycle detection: per subset, iteratively trim
          every node with no live predecessor or successor, where
          pred/succ come from the sparse ww/wr/rw edge columns plus
          ANALYTIC realtime/process interval bounds (builder metadata
          from elle/build.py) instead of materialized O(N^2) edges.
          Nonempty fixpoint core <=> cycle. O((E + N) x S) per round,
          no N^2 anywhere — the shape that wins when the graph is
          sparse relative to N^3/32, which includes every elle bench
          config on a plain CPU backend; valid histories decide
          entirely on device (empty cores), anomalies hand a tiny
          core to the host explainer.

The reference's Elle (dependency-graph cycle search over txn histories,
wrapped at jepsen/src/jepsen/tests/cycle/append.clj:11-22 and wr.clj:
14-53) walks graphs with DFS on the JVM. SURVEY.md flags it as the
phase-2 TPU target: "SCC/cycle detection as sparse matrix ops". This
module is that pass, designed MXU-first rather than as a graph-walk
translation:

  adjacency  A[s]        one (N, N) 0/1 matrix per edge-type subset s
                         (G0 wants ww-only, G1c ww+wr, G2 adds rw),
                         scattered from the DepGraph's (E, 3) edge
                         columns in one indexed update — the subsets
                         ride a leading batch axis, so all closures
                         compute in lockstep.
  closure    R = (A|I)^(2^k)   repeated squaring under lax.fori_loop:
                         ceil(log2(N)) batched matmuls, each a bf16
                         (N, N) @ (N, N) on the MXU with f32
                         accumulation, re-binarized after every step.
                         Static iteration count — no data-dependent
                         control flow, one compile per shape bucket.
  SCCs       mutual = R & R^T; label[i] = min{j : mutual[i, j]}
                         a nontrivial SCC exists iff label != arange.
  rw queries G-single / G2 ask "is some rw edge (s, d) closed by a
                         path d -> s?" — per-edge BFS on the host
                         (O(rw_edges * E), the host path's hot spot),
                         but a single gather R[:, dst, src] here.

Verdicts come off the device; *explanations* stay on the host: when a
query fires, the caller re-derives the concrete cycle by BFS restricted
to the flagged component / edge, which is tiny. This mirrors the WGL
split (device decides, host explains counterexamples).

bf16 safety: matmul entries count paths (up to N); bf16 rounds integers
above 256, but every addend is >= 0 and rounding is to-nearest, so a
positive sum can never round to zero — and only (sum > 0) is consumed.

Capacity: dense (S, N, N) closure is the right trade below ~8k txns.
At the 8192 cap each bf16 subset matrix is 8192^2 * 2 B = 128 MiB, and
the kernel holds S=3 of them plus the f32 einsum product and the
mutual/transpose temporaries — peak live bytes ~1 GiB, comfortably
inside a v5e's 16 GiB HBM. One squaring is ~2 * 3 * 8192^3 flops
=~ 3.3 TFLOP across the batch, ~17 ms at v5e bf16 peak (197 TFLOP/s).
Histories past the cap — BASELINE's independent configs shard per key
long before that — fall back to the host oracle, recorded in the
result.
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import Optional, Sequence

import numpy as np

from .graph import (PROCESS, REALTIME, RW, WR, WW, DepGraph,
                    _bfs_path)

# The standard Elle query battery (append.clj / wr.clj semantics).
# Subsets are cumulative: S0 (G0) < S1 (G1c, and the G-single closure)
# < S2 (the G2 closure).
SUBSETS = (
    frozenset({WW, REALTIME, PROCESS}),
    frozenset({WW, WR, REALTIME, PROCESS}),
    frozenset({WW, WR, RW, REALTIME, PROCESS}),
)

DEFAULT_MAX_N = 8192


def _round_up(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def _bucket(n: int) -> int:
    """Next power of two, so jit recompiles stay logarithmic in size."""
    return max(1, 1 << (int(n) - 1).bit_length())


def _n_pad_for(n: int) -> int:
    """The dense kernels' shared row padding: pow2 bucket, room for
    the two query-pad sentinels, rounded to the 128 sublane multiple.
    One derivation for cycle_queries / cycle_queries_packed /
    cycle_queries_sharded / shape_bucket_for — the sharded==packed
    bit-identity contract needs all of them on the SAME n_pad."""
    return _round_up(max(_bucket(max(n, 2)), n + 2), 128)


def make_closure_kernel(n_pad: int, n_sub: int, iters: int, dtype):
    """The closure-by-squaring kernel as a plain traceable function —
    shared by the runtime path below and the AOT TPU-evidence path
    (ops/aot.py), which lowers it for a v5e topology in bf16."""
    import jax
    import jax.numpy as jnp

    def kernel(src, dst, w, q_src, q_dst):
        # adjacency per subset: (S, N, N); padded edges carry w == 0
        adj = jnp.zeros((n_sub, n_pad, n_pad), dtype)
        adj = adj.at[:, src, dst].max(w.astype(dtype))
        eye = jnp.eye(n_pad, dtype=dtype)
        reach = jnp.maximum(adj, eye[None])

        # per-iteration frontier of the label propagation: reachable
        # pair count per subset after each squaring — the closure's
        # occupancy counters, returned with the verdict outputs so
        # they ride the SAME device->host fetch (no extra transfer,
        # doc/OBSERVABILITY.md "Occupancy & roofline")
        counts0 = jnp.zeros((iters, n_sub), jnp.int32)

        # Convergence early-exit (ROADMAP item 2's reclaimable
        # squarings, exposed by PR 8's converged_at counters): reach
        # under repeated squaring is monotone and idempotent at the
        # fixed point, so once the per-subset pair counts repeat the
        # remaining scheduled squarings are pure wasted MXU work —
        # stop there. Outputs are bit-identical to the fixed
        # schedule; `iters_run` reports what actually executed.
        def cond(st):
            _, _, i, changed = st
            return (i < iters) & changed

        def square(st):
            r, cnt, i, _ = st
            prod = jnp.einsum("sij,sjk->sik", r, r,
                              preferred_element_type=jnp.float32)
            r2 = (prod > 0).astype(dtype)
            c = jnp.sum((r2 > 0).astype(jnp.int32), axis=(1, 2))
            prev = jnp.where(i > 0, cnt[jnp.maximum(i - 1, 0)],
                             jnp.full((n_sub,), -1, jnp.int32))
            cnt = cnt.at[i].set(c)
            return r2, cnt, i + 1, jnp.any(c != prev)

        reach, counts, iters_run, _ = jax.lax.while_loop(
            cond, square, (reach, counts0, jnp.int32(0),
                           jnp.asarray(True)))
        rb = reach > 0
        mutual = rb & jnp.swapaxes(rb, 1, 2)
        cols = jnp.arange(n_pad, dtype=jnp.int32)
        labels = jnp.where(mutual, cols[None, None, :],
                           n_pad).min(axis=2)
        # rw-closure queries: path q_dst -> q_src under each subset
        closed = rb[:, q_dst, q_src]
        return labels.astype(jnp.int32), closed, counts, iters_run

    return kernel


@lru_cache(maxsize=32)
def _compiled(n_pad: int, e_pad: int, q_pad: int, n_sub: int,
              iters: int):
    """The closure kernel for one shape bucket, AOT-compiled so the
    compile cost is measured here (once per bucket) and callers time
    pure execution — no double-run for telemetry. Returns
    (compiled_fn, compile_s)."""
    import time as _t

    import jax
    import jax.numpy as jnp

    from ..util import safe_backend

    # lock-free platform probe: jax.default_backend() would trigger
    # backend init itself, ahead of the bounded-wait policy upstream
    dtype = jnp.bfloat16 if safe_backend() == "tpu" else jnp.float32
    kernel = make_closure_kernel(n_pad, n_sub, iters, dtype)

    specs = (jax.ShapeDtypeStruct((e_pad,), jnp.int32),
             jax.ShapeDtypeStruct((e_pad,), jnp.int32),
             jax.ShapeDtypeStruct((n_sub, e_pad), jnp.float32),
             jax.ShapeDtypeStruct((q_pad,), jnp.int32),
             jax.ShapeDtypeStruct((q_pad,), jnp.int32))
    t0 = _t.monotonic()
    compiled = jax.jit(kernel).lower(*specs).compile()
    return compiled, _t.monotonic() - t0


def cycle_queries(g: DepGraph,
                  subsets: Sequence[frozenset] = SUBSETS,
                  rw_type: int = RW,
                  max_n: int = DEFAULT_MAX_N) -> Optional[dict]:
    """Run the batched closure over `subsets` and the rw-closure
    queries on the device. Returns
      {"sccs": [per-subset list of >1-node components (history ids)],
       "rw_edges": [(src, dst) history ids],
       "rw_closed": (S, n_rw) bool — rw edge closed under subset s}
    or None when the graph exceeds max_n (caller falls back to host).
    """
    nodes = g.nodes
    n = int(nodes.shape[0])
    if n > max_n:
        return None
    edges = g.edges
    id_of = {int(v): i for i, v in enumerate(nodes)}

    # padding nodes are isolated; n_pad >= n + 2 guarantees two distinct
    # isolated nodes for the padded (always-False) rw queries
    n_pad = _round_up(max(_bucket(n), n + 2), 128)
    src = np.array([id_of[int(s)] for s in edges[:, 0]], np.int32)
    dst = np.array([id_of[int(d)] for d in edges[:, 1]], np.int32)
    typ = edges[:, 2]
    n_sub = len(subsets)
    w = np.zeros((n_sub, len(src)), np.float32)
    for si, sub in enumerate(subsets):
        w[si] = np.isin(typ, list(sub)).astype(np.float32)

    rw_mask = typ == rw_type
    q_src, q_dst = src[rw_mask], dst[rw_mask]
    rw_edges = [(int(edges[i, 0]), int(edges[i, 1]))
                for i in np.flatnonzero(rw_mask)]

    e_pad = _bucket(max(len(src), 1))
    q_pad = _bucket(max(len(q_src), 1))

    def pad(a, size, fill):
        out = np.full(size, fill, a.dtype if len(a) else np.int32)
        out[:len(a)] = a
        return out

    src_p = pad(src, e_pad, 0)
    dst_p = pad(dst, e_pad, 0)
    w_p = np.zeros((n_sub, e_pad), np.float32)
    w_p[:, :w.shape[1]] = w
    # padded queries land on distinct isolated padding nodes -> False
    q_src_p = pad(q_src, q_pad, n_pad - 1)
    q_dst_p = pad(q_dst, q_pad, n_pad - 2)

    iters = max(1, math.ceil(math.log2(n_pad)))
    kernel, compile_s = _compiled(n_pad, e_pad, q_pad, n_sub, iters)
    import time as _t

    import jax

    from ..analysis import guards as _guards
    from .. import watchdog as _watchdog
    t0 = _t.monotonic()
    ins = (np.asarray(src_p, np.int32), np.asarray(dst_p, np.int32),
           np.asarray(w_p, np.float32), np.asarray(q_src_p, np.int32),
           np.asarray(q_dst_p, np.int32))
    _guards.note_transfer("h2d", sum(a.nbytes for a in ins),
                          what="elle-closure-inputs")
    # watchdog coverage for the one blocking device call here: the
    # closure kernel has no poll loop to heartbeat from, so the beat
    # lands just before the call — a hung MXU dispatch leaves the
    # source beating-silent and the monitor flags it (doc/
    # OBSERVABILITY.md "stall watchdog")
    wd = _watchdog.get_default()
    dm, dmark = _hbm_mark()
    # stall_s override: the closure at capacity is a known-slow
    # healthy call (BENCH_r04: ~57 s of dense f32 matmuls on cpu) —
    # only a multi-minute silence is a hang here
    with wd.watch("elle-closure", device="tpu",
                  stall_s=300.0) as hb:
        wd.beat(hb, edges=int(len(src)), n=n, n_pad=n_pad, iters=iters)
        labels, closed, iter_counts, iters_run = kernel(*ins)
        jax.block_until_ready((labels, closed, iter_counts, iters_run))
    kernel_s = _t.monotonic() - t0
    # Convergence early-exit (make_closure_kernel): the device loop
    # stopped after `iters_run` squarings; the rest of the fixed
    # schedule is reclaimed MXU work, reported below.
    iters_run = max(1, int(iters_run))
    # Achieved matmul throughput vs the flop model in the module
    # docstring: iters_run squarings x n_sub batched (n_pad)^3
    # matmuls — the work that actually executed.
    flops = 2.0 * n_sub * iters_run * float(n_pad) ** 3
    # per-iteration frontier (occupancy plane): reachable-pair counts
    # per subset after each executed squaring, and the first
    # iteration at which the widest subset's closure stopped growing
    iter_counts = np.asarray(iter_counts)[:iters_run]  # (run, n_sub)
    iter_reach = [[int(v) for v in row] for row in iter_counts]
    widest = iter_counts[:, -1]
    converged_at = int(iters_run)
    for i in range(1, iters_run):
        if widest[i] == widest[i - 1]:
            converged_at = i
            break
    util = {"n_pad": n_pad, "iters": iters,
            "iters_run": iters_run,
            "iters_reclaimed": int(iters) - iters_run,
            "kernel_s": round(kernel_s, 4),
            "compile_s": round(compile_s, 3),
            "achieved_tflops": round(flops / 1e12 / max(kernel_s, 1e-9),
                                     2),
            "iter_reach": iter_reach,
            "converged_at": converged_at,
            "reach_density": round(
                float(widest[-1]) / float(n_pad) ** 2, 6)}
    _hbm_close(util, dm, dmark)
    # the MXU plane's telemetry rides the same registry as the
    # search kernels' (doc/OBSERVABILITY.md)
    _record_closure(util, len(src), n)
    labels = np.asarray(labels)[:, :n]
    closed = np.asarray(closed)[:, :len(rw_edges)]
    _guards.note_transfer("d2h",
                          labels.nbytes + closed.nbytes
                          + iter_counts.nbytes,
                          what="elle-closure-outputs")

    sccs: list = []
    for si in range(n_sub):
        comps: dict = {}
        for i in range(n):
            lab = int(labels[si, i])
            if lab != i:
                comps.setdefault(lab, [int(nodes[lab])]).append(
                    int(nodes[i]))
        sccs.append([sorted(c) for c in comps.values()])
    return {"sccs": sccs, "rw_edges": rw_edges, "rw_closed": closed,
            "util": util}


PACKED_MAX_N = 32768

# the column-sharded closure's own row cap: at 131072 the full packed
# bitset is S * N^2 / 8 = 6.4 GB — one gathered copy per shard plus
# 2/n_shards local blocks fits a 16 GiB chip from 4 shards up. Past
# this even the gather buffer alone blows a v5e, so the cap is a row
# count, not a fleet question.
SHARDED_MAX_N = 131072


def _hbm_mark():
    """Open a device-observatory window around one closure-kernel
    call (devices.py): returns (monitor, token) — token None when the
    ambient monitor is disabled, so the hot path pays one attribute
    check."""
    from .. import devices as _devices
    dm = _devices.get_default()
    return dm, (dm.mark(where="elle-closure") if dm.enabled else None)


def _hbm_close(util: dict, dm, dmark) -> None:
    """Close the window onto the util block: `hbm` carries the full
    measured block (explicit stats_unavailable marker on statless
    backends) and `hbm_peak_measured` the scalar the ledger/bench
    drift gate compares against preflight's analytic prediction."""
    if dmark is None:
        return
    block = dm.measured(dmark, where="elle-closure")
    util["hbm"] = block
    if block.get("peak_measured") is not None:
        util["hbm_peak_measured"] = block["peak_measured"]


def _record_closure(util: dict, edges: int, n: int) -> None:
    """elle_closure series + counters — every device kernel variant
    feeds the same registry — plus an `elle` strip on the live
    occupancy block, so /occupancy and /status.json cover the Elle
    plane next to the WGL kernels (doc/OBSERVABILITY.md)."""
    from .. import fleet as _fleet
    from .. import metrics as _metrics
    mx = _metrics.get_default()
    if mx.enabled:
        mx.series("elle_closure",
                  "per-call Elle closure-kernel telemetry").append(
            {"edges": int(edges), "n": int(n), **util})
        mx.counter("elle_closure_calls_total",
                   "batched closure kernel invocations").inc()
        mx.histogram("elle_closure_seconds",
                     "closure kernel wall (post-compile)").observe(
            float(util.get("kernel_s") or 0.0))
    st = _fleet.get_default()
    if st.enabled:
        st.occupancy_poll({"elle": {
            "kernel": util.get("kernel", "bf16"), "n": int(n),
            "edges": int(edges),
            "iters_run": util.get("iters_run"),
            "kernel_s": util.get("kernel_s"),
            "reach_density": util.get("reach_density")}},
            search_id="elle")


# -- packed closure: uint32 bitset squaring ---------------------------------

def make_packed_closure_kernel(n_pad: int, n_sub: int, iters: int):
    """The closure-by-squaring kernel over uint32 bitset words:
    (S, N, N/32) storage, 16x less than bf16, capacity lifted to
    PACKED_MAX_N. The squaring R2[i] = OR_{j : R[i] bit j} R[j] scans
    32-column blocks: extract the block's i->j bits from one word
    column, AND/OR-reduce the block's 32 packed rows into the
    accumulator. Outputs (labels, closed, counts, iters_run) are
    BIT-IDENTICAL to make_closure_kernel's — same convergence
    schedule, counts by popcount — which tests/test_elle_tpu.py and
    the CI elle smoke gate pin."""
    import jax
    import jax.numpy as jnp

    W = n_pad // 32
    word_idx = np.arange(n_pad, dtype=np.int32) // 32
    bit_idx = (np.arange(n_pad, dtype=np.int32) % 32).astype(np.uint32)

    def kernel(r0, q_src, q_dst):
        counts0 = jnp.zeros((iters, n_sub), jnp.int32)

        def square(r):
            def blk(acc, jb):
                rows_j = jax.lax.dynamic_slice(
                    r, (0, jb * 32, 0), (n_sub, 32, W))
                word_i = jax.lax.dynamic_slice(
                    r, (0, 0, jb), (n_sub, n_pad, 1))[..., 0]
                # intentional bounded unroll: exactly the 32 bits
                # of one packed word per block
                for k in range(32):  # jaxlint: ok(J006)
                    bit = (word_i >> jnp.uint32(k)) & jnp.uint32(1)
                    acc = acc | (bit[:, :, None]
                                 * rows_j[:, k][:, None, :])
                return acc, None
            out, _ = jax.lax.scan(blk, jnp.zeros_like(r),
                                  jnp.arange(W))
            return out

        def cond(st):
            _, _, i, changed = st
            return (i < iters) & changed

        def step(st):
            r, cnt, i, _ = st
            r2 = square(r)
            c = jnp.sum(jax.lax.population_count(r2).astype(jnp.int32),
                        axis=(1, 2))
            prev = jnp.where(i > 0, cnt[jnp.maximum(i - 1, 0)],
                             jnp.full((n_sub,), -1, jnp.int32))
            cnt = cnt.at[i].set(c)
            return r2, cnt, i + 1, jnp.any(c != prev)

        reach, counts, iters_run, _ = jax.lax.while_loop(
            cond, step, (r0, counts0, jnp.int32(0), jnp.asarray(True)))

        # labels[i] = min{j : reach[i,j] & reach[j,i]}, scanned over
        # 32-column blocks of the packed closure
        cols32 = jnp.arange(32, dtype=jnp.int32)

        def lab_blk(lab, jb):
            bits_ij = (jax.lax.dynamic_slice(
                reach, (0, 0, jb), (n_sub, n_pad, 1))[..., 0][:, :, None]
                >> cols32[None, None, :].astype(jnp.uint32)) \
                & jnp.uint32(1)                          # (S, N, 32)
            rows_j = jax.lax.dynamic_slice(
                reach, (0, jb * 32, 0), (n_sub, 32, W))  # (S, 32, W)
            bits_ji = (jnp.take(rows_j, jnp.asarray(word_idx), axis=2)
                       >> bit_idx[None, None, :]) & jnp.uint32(1)
            mutual = (bits_ij & jnp.moveaxis(bits_ji, 1, 2)) > 0
            jcol = jb * 32 + cols32
            cand = jnp.min(jnp.where(mutual, jcol[None, None, :],
                                     n_pad), axis=2)
            return jnp.minimum(lab, cand), None

        labels, _ = jax.lax.scan(
            lab_blk, jnp.full((n_sub, n_pad), n_pad, jnp.int32),
            jnp.arange(W))

        words = reach[:, q_dst, q_src // 32]             # (S, Q)
        closed = ((words >> (q_src % 32).astype(jnp.uint32))
                  & jnp.uint32(1)) > 0
        return labels, closed, counts, iters_run

    return kernel


@lru_cache(maxsize=32)
def _compiled_packed(n_pad: int, q_pad: int, n_sub: int, iters: int):
    import time as _t

    import jax
    import jax.numpy as jnp

    kernel = make_packed_closure_kernel(n_pad, n_sub, iters)
    specs = (jax.ShapeDtypeStruct((n_sub, n_pad, n_pad // 32),
                                  jnp.uint32),
             jax.ShapeDtypeStruct((q_pad,), jnp.int32),
             jax.ShapeDtypeStruct((q_pad,), jnp.int32))
    t0 = _t.monotonic()
    compiled = jax.jit(kernel).lower(*specs).compile()
    return compiled, _t.monotonic() - t0


def _graph_arrays(g, subsets, rw_type):
    """Shared edge-column prep for the squaring kernels: local ids,
    per-subset weights, rw query endpoints."""
    nodes = g.nodes
    n = int(nodes.shape[0])
    edges = np.asarray(g.edges)
    id_of = {int(v): i for i, v in enumerate(nodes)}
    src = np.array([id_of[int(s)] for s in edges[:, 0]], np.int32)
    dst = np.array([id_of[int(d)] for d in edges[:, 1]], np.int32)
    typ = edges[:, 2]
    n_sub = len(subsets)
    w = np.zeros((n_sub, len(src)), np.float32)
    for si, sub in enumerate(subsets):
        w[si] = np.isin(typ, list(sub)).astype(np.float32)
    rw_mask = typ == rw_type
    q_src, q_dst = src[rw_mask], dst[rw_mask]
    rw_edges = [(int(edges[i, 0]), int(edges[i, 1]))
                for i in np.flatnonzero(rw_mask)]
    return nodes, n, src, dst, w, q_src, q_dst, rw_edges


def _sccs_from_labels(labels, nodes, n, n_sub):
    sccs: list = []
    for si in range(n_sub):
        comps: dict = {}
        for i in range(n):
            lab = int(labels[si, i])
            if lab != i:
                comps.setdefault(lab, [int(nodes[lab])]).append(
                    int(nodes[i]))
        sccs.append([sorted(c) for c in comps.values()])
    return sccs


def cycle_queries_packed(g, subsets: Sequence[frozenset] = SUBSETS,
                         rw_type: int = RW,
                         max_n: int = PACKED_MAX_N) -> Optional[dict]:
    """cycle_queries on the uint32 bitset kernel: same result
    envelope, 16x less closure memory, capacity to PACKED_MAX_N.
    The packed adjacency (plus identity) is assembled host-side with
    one bitwise_or scatter per subset — E word-ops, negligible."""
    nodes, n, src, dst, w, q_src, q_dst, rw_edges = \
        _graph_arrays(g, subsets, rw_type)
    if n > max_n:
        return None
    n_sub = len(subsets)
    n_pad = _round_up(max(_bucket(n), n + 2), 128)
    Wn = n_pad // 32

    r0 = np.zeros((n_sub, n_pad, Wn), np.uint32)
    eye = np.arange(n_pad)
    np.bitwise_or.at(r0, (slice(None), eye, eye // 32),
                     np.uint32(1) << (eye % 32).astype(np.uint32))
    for si in range(n_sub):
        m = w[si] > 0
        if m.any():
            np.bitwise_or.at(
                r0[si], (src[m], dst[m] // 32),
                np.uint32(1) << (dst[m] % 32).astype(np.uint32))

    q_pad = _bucket(max(len(q_src), 1))

    def pad(a, size, fill):
        out = np.full(size, fill, np.int32)
        out[:len(a)] = a
        return out

    q_src_p = pad(q_src, q_pad, n_pad - 1)
    q_dst_p = pad(q_dst, q_pad, n_pad - 2)
    iters = max(1, math.ceil(math.log2(n_pad)))
    kernel, compile_s = _compiled_packed(n_pad, q_pad, n_sub, iters)

    import time as _t

    import jax

    from ..analysis import guards as _guards
    from .. import watchdog as _watchdog
    t0 = _t.monotonic()
    _guards.note_transfer("h2d", r0.nbytes + q_src_p.nbytes
                          + q_dst_p.nbytes,
                          what="elle-closure-inputs")
    wd = _watchdog.get_default()
    dm, dmark = _hbm_mark()
    with wd.watch("elle-closure", device="tpu", stall_s=300.0) as hb:
        wd.beat(hb, edges=int(len(src)), n=n, n_pad=n_pad,
                iters=iters, kernel="packed")
        labels, closed, iter_counts, iters_run = kernel(
            r0, q_src_p, q_dst_p)
        jax.block_until_ready((labels, closed, iter_counts, iters_run))
    kernel_s = _t.monotonic() - t0
    iters_run = max(1, int(iters_run))
    iter_counts = np.asarray(iter_counts)[:iters_run]
    iter_reach = [[int(v) for v in row] for row in iter_counts]
    widest = iter_counts[:, -1]
    converged_at = int(iters_run)
    for i in range(1, iters_run):
        if widest[i] == widest[i - 1]:
            converged_at = i
            break
    # word-ops model: one squaring ANDs/ORs n_pad^2 * W words/subset
    gops = 2.0 * n_sub * iters_run * float(n_pad) ** 2 * Wn / 1e9
    util = {"kernel": "packed", "n_pad": n_pad, "iters": iters,
            "iters_run": iters_run,
            "iters_reclaimed": int(iters) - iters_run,
            "kernel_s": round(kernel_s, 4),
            "compile_s": round(compile_s, 3),
            "achieved_gops": round(gops / max(kernel_s, 1e-9), 2),
            "closure_bytes": int(r0.nbytes),
            "iter_reach": iter_reach,
            "converged_at": converged_at,
            "reach_density": round(
                float(widest[-1]) / float(n_pad) ** 2, 6)}
    _hbm_close(util, dm, dmark)
    _record_closure(util, len(src), n)
    labels = np.asarray(labels)[:, :n]
    closed = np.asarray(closed)[:, :len(rw_edges)]
    _guards.note_transfer("d2h", labels.nbytes + closed.nbytes
                          + iter_counts.nbytes,
                          what="elle-closure-outputs")
    return {"sccs": _sccs_from_labels(labels, nodes, n, len(subsets)),
            "rw_edges": rw_edges, "rw_closed": closed, "util": util}


# -- sharded closure: word columns across the mesh --------------------------

def make_sharded_closure_kernel(n_pad: int, n_sub: int, iters: int,
                                n_shards: int, axis: str = "words"):
    """make_packed_closure_kernel past single-chip HBM: the
    (S, N, N/32) word-column axis is sharded across a 1-D device mesh
    — each shard owns a contiguous block of W/n_shards word columns
    and ONE `all_gather` per squaring iteration exchanges the row set
    (the full packed reach), so every shard can test its rows'
    out-neighbor bits over ALL columns while writing only its own
    column block. Per-shard live bytes are the gather buffer plus two
    local blocks =~ bitset * (1 + 2/n_shards), vs CLOSURE_LIVE_FACTOR
    full copies unsharded — the bill preflight.plan_elle_sharded
    reproduces.

    Convergence is decided GLOBALLY: per-shard popcounts are
    psum-reduced over the mesh axis before the repeat-count compare,
    so every shard runs the identical trip count even when an
    iteration only flips bits inside one shard's column block (a
    per-shard compare would deadlock the collective schedule — the
    cross-shard-cycle regression in tests/test_elle_sharded.py).
    Outputs (labels, closed, counts, iters_run) are BIT-IDENTICAL to
    the unsharded packed kernel's: same n_pad, same 32-column block
    schedule, same popcount convergence — pinned by the CI elle
    smoke's sharded==packed section."""
    import jax
    import jax.numpy as jnp

    W = n_pad // 32
    if W % n_shards:
        raise ValueError(f"W {W} not divisible by {n_shards} shards")
    w_loc = W // n_shards
    word_idx = np.arange(n_pad, dtype=np.int32) // 32
    bit_idx = (np.arange(n_pad, dtype=np.int32) % 32).astype(np.uint32)

    def kernel(r_loc, q_src, q_dst):
        counts0 = jnp.zeros((iters, n_sub), jnp.int32)

        def square(r):
            # the ONE collective per squaring iteration: every shard
            # rematerializes the full row set to enumerate j-bits
            full = jax.lax.all_gather(r, axis, axis=2, tiled=True)

            def blk(acc, jb):
                rows_j = jax.lax.dynamic_slice(
                    r, (0, jb * 32, 0), (n_sub, 32, w_loc))
                word_i = jax.lax.dynamic_slice(
                    full, (0, 0, jb), (n_sub, n_pad, 1))[..., 0]
                # intentional bounded unroll: exactly the 32 bits
                # of one packed word per block
                for k in range(32):  # jaxlint: ok(J006)
                    bit = (word_i >> jnp.uint32(k)) & jnp.uint32(1)
                    acc = acc | (bit[:, :, None]
                                 * rows_j[:, k][:, None, :])
                return acc, None
            out, _ = jax.lax.scan(blk, jnp.zeros_like(r),
                                  jnp.arange(W))
            return out

        def cond(st):
            _, _, i, changed = st
            return (i < iters) & changed

        def step(st):
            r, cnt, i, _ = st
            r2 = square(r)
            c_loc = jnp.sum(
                jax.lax.population_count(r2).astype(jnp.int32),
                axis=(1, 2))
            # the early-exit must compare GLOBAL reach counts: a
            # per-shard compare would let a shard whose column block
            # went quiet leave the loop while a neighbor still grows
            # bits — divergent trip counts under a collective
            c = jax.lax.psum(c_loc, axis)
            prev = jnp.where(i > 0, cnt[jnp.maximum(i - 1, 0)],
                             jnp.full((n_sub,), -1, jnp.int32))
            cnt = cnt.at[i].set(c)
            return r2, cnt, i + 1, jnp.any(c != prev)

        reach_loc, counts, iters_run, _ = jax.lax.while_loop(
            cond, step, (r_loc, counts0, jnp.int32(0),
                         jnp.asarray(True)))

        # labels + rw answers need the FULL closure: one final gather,
        # then the packed kernel's label scan verbatim — replicated
        # work on every shard, identical inputs -> identical outputs
        reach = jax.lax.all_gather(reach_loc, axis, axis=2,
                                   tiled=True)
        cols32 = jnp.arange(32, dtype=jnp.int32)

        def lab_blk(lab, jb):
            bits_ij = (jax.lax.dynamic_slice(
                reach, (0, 0, jb), (n_sub, n_pad, 1))[..., 0][:, :, None]
                >> cols32[None, None, :].astype(jnp.uint32)) \
                & jnp.uint32(1)                          # (S, N, 32)
            rows_j = jax.lax.dynamic_slice(
                reach, (0, jb * 32, 0), (n_sub, 32, W))  # (S, 32, W)
            bits_ji = (jnp.take(rows_j, jnp.asarray(word_idx), axis=2)
                       >> bit_idx[None, None, :]) & jnp.uint32(1)
            mutual = (bits_ij & jnp.moveaxis(bits_ji, 1, 2)) > 0
            jcol = jb * 32 + cols32
            cand = jnp.min(jnp.where(mutual, jcol[None, None, :],
                                     n_pad), axis=2)
            return jnp.minimum(lab, cand), None

        labels, _ = jax.lax.scan(
            lab_blk, jnp.full((n_sub, n_pad), n_pad, jnp.int32),
            jnp.arange(W))

        words = reach[:, q_dst, q_src // 32]             # (S, Q)
        closed = ((words >> (q_src % 32).astype(jnp.uint32))
                  & jnp.uint32(1)) > 0
        return labels, closed, counts, iters_run

    return kernel


@lru_cache(maxsize=16)
def _compiled_sharded(n_pad: int, q_pad: int, n_sub: int, iters: int,
                      n_shards: int):
    """AOT-compiled sharded closure: the shard_map program plus the
    mesh it is laid out over, so the runtime path and the AOT warm
    path (aot.precompile_elle_closure) hit ONE executable per
    (shape, shard count) bucket — the zero-recompile warm contract."""
    import time as _t

    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel.mesh import words_mesh

    mesh = words_mesh(n_shards)
    kernel = make_sharded_closure_kernel(n_pad, n_sub, iters, n_shards)
    spec_r = PartitionSpec(None, None, "words")
    spec_0 = PartitionSpec()
    sharded = shard_map(kernel, mesh=mesh,
                        in_specs=(spec_r, spec_0, spec_0),
                        out_specs=(spec_0, spec_0, spec_0, spec_0),
                        check_rep=False)
    specs = (jax.ShapeDtypeStruct((n_sub, n_pad, n_pad // 32),
                                  jnp.uint32,
                                  sharding=NamedSharding(mesh, spec_r)),
             jax.ShapeDtypeStruct((q_pad,), jnp.int32,
                                  sharding=NamedSharding(mesh, spec_0)),
             jax.ShapeDtypeStruct((q_pad,), jnp.int32,
                                  sharding=NamedSharding(mesh, spec_0)))
    t0 = _t.monotonic()
    compiled = jax.jit(sharded).lower(*specs).compile()
    return compiled, mesh, _t.monotonic() - t0


def cycle_queries_sharded(g, subsets: Sequence[frozenset] = SUBSETS,
                          rw_type: int = RW,
                          max_n: int = SHARDED_MAX_N,
                          n_shards: Optional[int] = None
                          ) -> Optional[dict]:
    """cycle_queries_packed past single-chip HBM: same host-assembled
    packed r0, same result envelope, word columns sharded across the
    "words" mesh. Each device receives ONLY its column block
    (device_put against the mesh sharding — the full bitset never
    lives on one chip), and per-shard HBM is billed up front by
    preflight.plan_elle_sharded. Returns None over capacity or when
    the fleet yields fewer than 2 shards (the caller falls back to
    packed/host); pass n_shards explicitly to pin a layout — tests
    pin n_shards=1 to run this path on a single device."""
    nodes, n, src, dst, w, q_src, q_dst, rw_edges = \
        _graph_arrays(g, subsets, rw_type)
    if n > max_n:
        return None
    n_sub = len(subsets)
    n_pad = _n_pad_for(n)
    Wn = n_pad // 32
    forced = n_shards is not None
    if n_shards is None:
        from ..parallel.mesh import word_shard_count
        n_shards = word_shard_count(Wn)
    if n_shards < 1 or Wn % n_shards \
            or (n_shards < 2 and not forced):
        return None

    r0 = np.zeros((n_sub, n_pad, Wn), np.uint32)
    eye = np.arange(n_pad)
    np.bitwise_or.at(r0, (slice(None), eye, eye // 32),
                     np.uint32(1) << (eye % 32).astype(np.uint32))
    for si in range(n_sub):
        m = w[si] > 0
        if m.any():
            np.bitwise_or.at(
                r0[si], (src[m], dst[m] // 32),
                np.uint32(1) << (dst[m] % 32).astype(np.uint32))

    q_pad = _bucket(max(len(q_src), 1))

    def pad(a, size, fill):
        out = np.full(size, fill, np.int32)
        out[:len(a)] = a
        return out

    q_src_p = pad(q_src, q_pad, n_pad - 1)
    q_dst_p = pad(q_dst, q_pad, n_pad - 2)
    iters = max(1, math.ceil(math.log2(n_pad)))
    kernel, mesh, compile_s = _compiled_sharded(
        n_pad, q_pad, n_sub, iters, n_shards)

    import time as _t

    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from ..analysis import guards as _guards
    from .. import watchdog as _watchdog
    t0 = _t.monotonic()
    _guards.note_transfer("h2d", r0.nbytes + q_src_p.nbytes
                          + q_dst_p.nbytes,
                          what="elle-closure-inputs")
    # pre-sharded placement: each device holds its 1/n_shards column
    # block; only the kernel's all_gather ever materializes the full
    # row set, and only transiently inside the squaring loop
    r0_d = jax.device_put(r0, NamedSharding(
        mesh, PartitionSpec(None, None, "words")))
    qs_d = jax.device_put(q_src_p,
                          NamedSharding(mesh, PartitionSpec()))
    qd_d = jax.device_put(q_dst_p,
                          NamedSharding(mesh, PartitionSpec()))
    wd = _watchdog.get_default()
    dm, dmark = _hbm_mark()
    with wd.watch("elle-closure", device="tpu", stall_s=300.0) as hb:
        wd.beat(hb, edges=int(len(src)), n=n, n_pad=n_pad,
                iters=iters, kernel="sharded", n_shards=n_shards)
        labels, closed, iter_counts, iters_run = kernel(
            r0_d, qs_d, qd_d)
        jax.block_until_ready((labels, closed, iter_counts, iters_run))
    kernel_s = _t.monotonic() - t0
    iters_run = max(1, int(iters_run))
    iter_counts = np.asarray(iter_counts)[:iters_run]
    iter_reach = [[int(v) for v in row] for row in iter_counts]
    widest = iter_counts[:, -1]
    converged_at = int(iters_run)
    for i in range(1, iters_run):
        if widest[i] == widest[i - 1]:
            converged_at = i
            break
    gops = 2.0 * n_sub * iters_run * float(n_pad) ** 2 * Wn / 1e9
    util = {"kernel": "sharded", "n_pad": n_pad, "iters": iters,
            "iters_run": iters_run,
            "iters_reclaimed": int(iters) - iters_run,
            "n_shards": int(n_shards),
            "shard_words": Wn // n_shards,
            "gather_bytes": int(r0.nbytes),
            "per_shard_bytes": int(r0.nbytes
                                   + 2 * r0.nbytes // n_shards),
            "kernel_s": round(kernel_s, 4),
            "compile_s": round(compile_s, 3),
            "achieved_gops": round(gops / max(kernel_s, 1e-9), 2),
            "closure_bytes": int(r0.nbytes),
            "iter_reach": iter_reach,
            "converged_at": converged_at,
            "reach_density": round(
                float(widest[-1]) / float(n_pad) ** 2, 6)}
    _hbm_close(util, dm, dmark)
    _record_closure(util, len(src), n)
    labels = np.asarray(labels)[:, :n]
    closed = np.asarray(closed)[:, :len(rw_edges)]
    _guards.note_transfer("d2h", labels.nbytes + closed.nbytes
                          + iter_counts.nbytes,
                          what="elle-closure-outputs")
    return {"sccs": _sccs_from_labels(labels, nodes, n, len(subsets)),
            "rw_edges": rw_edges, "rw_closed": closed, "util": util}


# -- trim closure: peel-to-core cycle detection + interval jumps ------------

def make_trim_kernel(n_pad: int, d_in: int, d_out: int, n_sub: int,
                     p_pad: int, use_rt: bool, use_proc: bool,
                     counts_rows: int = 64):
    """Cycle EXISTENCE for the query battery by trimming: per subset,
    iteratively peel every node with no live predecessor or no live
    successor; the fixpoint ("core") is nonempty iff the subset has a
    cycle (every core node keeps an out-neighbor in the core, so a
    walk must revisit). Predecessors/successors come from

      * the sparse ww/wr/rw edges, as PADDED NEIGHBOR GATHERS
        (in/out adjacency lists padded to the degree bucket) — pure
        gather+reduce, because XLA's cpu scatter lowering makes a
        segment-max formulation ~25x slower per round (measured);
      * analytic realtime interval bounds in builder mode: a node has
        a realtime predecessor iff some live node's comp_evt lies
        below its inv_evt — per-subset min/argmin plus masked
        second-min scalars (second-min so a zero-duration op whose
        completion event precedes its own invocation cannot keep
        itself alive). The threshold pool is ANCHORED: only live
        nodes that already have non-realtime in-support (edge or
        process), plus inverted ops (comp < inv — the self-support
        hazard), contribute their comp to the in-threshold
        (symmetrically their inv to the out-threshold). Among normal
        ops a transitive realtime-support chain descends strictly in
        comp (comp_k < inv_j <= comp_j) and so terminates at an
        anchored or inverted node — the anchored rule has EXACTLY the
        same greatest fixpoint as pooling over all live nodes, but a
        realtime-only chain that the all-live pool peels one node per
        round collapses in a single round: round count drops from
        O(realtime span) to the edge-peel depth, O(log N) on the
        long-span adversarial corpora (tests/test_elle_tpu.py pins
        both the parity and the round bound);
      * process chains via per-process segment-min/max positions
        (strict compares, so self never qualifies).

    Work per round is O((E + N) x S) elementwise — no O(N^2)
    anywhere — and rounds are bounded by the edge-peel depth (~N /
    concurrency width for real histories; the safety bound is n_pad).
    Valid histories end with EMPTY cores: the device verdict alone
    answers all four queries and the host never builds a DepGraph; a
    nonempty core hands the (tiny) cyclic neighborhood to the host
    oracle for the concrete cycle ("device decides, host explains")."""
    import jax
    import jax.numpy as jnp

    def kernel(in_neigh, in_mask, out_neigh, out_mask,
               inv_e, comp_e, proc, ppos, live0):
        counts0 = jnp.zeros((counts_rows, n_sub), jnp.int32)
        BIGI = jnp.int32(2 ** 30)
        rows = jnp.arange(n_pad, dtype=jnp.int32)[:, None]

        def peel(live):
            has_in = jnp.any(live[in_neigh, :] & in_mask, axis=1)
            has_out = jnp.any(live[out_neigh, :] & out_mask, axis=1)
            if use_proc:
                pp_in = jnp.where(live, ppos[:, None], BIGI)
                minpp = jax.ops.segment_min(pp_in, proc,
                                            num_segments=p_pad)
                pp_out = jnp.where(live, ppos[:, None], -BIGI)
                maxpp = jax.ops.segment_max(pp_out, proc,
                                            num_segments=p_pad)
                has_in = has_in | (ppos[:, None] > minpp[proc, :])
                has_out = has_out | ((ppos[:, None] < maxpp[proc, :])
                                     & (ppos[:, None] >= 0))
            if use_rt:
                # anchored threshold pool (the interval scan): only
                # nodes with non-realtime support this round — plus
                # inverted ops, which could support themselves — can
                # anchor a realtime chain. Same fixpoint as pooling
                # over ALL live nodes (transitive rt support among
                # normal ops descends strictly in comp and lands on
                # an anchor), but whole rt chains peel per round
                # instead of one node per round.
                inverted = (comp_e < inv_e)[:, None]
                pool_in = live & (has_in | inverted)
                comp_pool = jnp.where(pool_in, comp_e[:, None], BIGI)
                minc1 = jnp.min(comp_pool, axis=0)
                minc_at = jnp.argmin(comp_pool, axis=0)
                minc2 = jnp.min(
                    jnp.where(rows == minc_at[None, :], BIGI,
                              comp_pool), axis=0)
                pool_out = live & (has_out | inverted)
                inv_pool = jnp.where(pool_out, inv_e[:, None], -BIGI)
                maxi1 = jnp.max(inv_pool, axis=0)
                maxi_at = jnp.argmax(inv_pool, axis=0)
                maxi2 = jnp.max(
                    jnp.where(rows == maxi_at[None, :], -BIGI,
                              inv_pool), axis=0)
                in_thr = jnp.where(rows == minc_at[None, :],
                                   minc2[None, :], minc1[None, :])
                out_thr = jnp.where(rows == maxi_at[None, :],
                                    maxi2[None, :], maxi1[None, :])
                has_in = has_in | (inv_e[:, None] > in_thr)
                has_out = has_out | (comp_e[:, None] < out_thr)
            return live & has_in & has_out

        def cond(st):
            _l, _c, i, changed = st
            return changed & (i < n_pad)

        def body(st):
            live, cnt, i, _ = st
            live = peel(peel(live))
            c = jnp.sum(live, axis=0, dtype=jnp.int32)
            prev = jnp.where(
                i > 0,
                cnt[jnp.minimum(jnp.maximum(i - 1, 0),
                                counts_rows - 1)],
                jnp.full((n_sub,), -1, jnp.int32))
            cnt = cnt.at[jnp.minimum(i, counts_rows - 1)].set(c)
            return live, cnt, i + 1, jnp.any(c != prev)

        live, counts, iters_run, _ = jax.lax.while_loop(
            cond, body, (live0, counts0, jnp.int32(0),
                         jnp.asarray(True)))
        # iters_run counts while-loop BODIES (= counts rows); each
        # body runs two peel rounds — the wrapper reports both
        return live, counts, iters_run

    return kernel


# degree buckets past this fall back to the dense kernels: a padded
# neighbor gather at that width would cost more than it saves
TRIM_MAX_DEGREE = 256


@lru_cache(maxsize=32)
def _compiled_trim(n_pad: int, d_in: int, d_out: int, n_sub: int,
                   p_pad: int, use_rt: bool, use_proc: bool):
    import time as _t

    import jax
    import jax.numpy as jnp

    kernel = make_trim_kernel(n_pad, d_in, d_out, n_sub, p_pad,
                              use_rt, use_proc)
    i32 = jnp.int32
    specs = (jax.ShapeDtypeStruct((n_pad, d_in), i32),
             jax.ShapeDtypeStruct((n_pad, d_in, n_sub), jnp.bool_),
             jax.ShapeDtypeStruct((n_pad, d_out), i32),
             jax.ShapeDtypeStruct((n_pad, d_out, n_sub), jnp.bool_),
             jax.ShapeDtypeStruct((n_pad,), i32),
             jax.ShapeDtypeStruct((n_pad,), i32),
             jax.ShapeDtypeStruct((n_pad,), i32),
             jax.ShapeDtypeStruct((n_pad,), i32),
             jax.ShapeDtypeStruct((n_pad, n_sub), jnp.bool_))
    t0 = _t.monotonic()
    compiled = jax.jit(kernel).lower(*specs).compile()
    return compiled, _t.monotonic() - t0


def _neighbor_pads(n_pad, e_from, e_to, w):
    """(neigh, mask) padded adjacency-list arrays: slot d of row j =
    d-th edge endpoint, mask carries the per-subset membership."""
    n_sub = w.shape[1]
    counts = np.bincount(e_to, minlength=n_pad)
    deg = int(counts.max()) if len(e_to) else 0
    d_pad = _bucket(max(deg, 4))
    if d_pad > TRIM_MAX_DEGREE:
        return None, None, d_pad
    order = np.argsort(e_to, kind="stable")
    to_s, from_s, w_s = e_to[order], e_from[order], w[order]
    starts = np.zeros(n_pad + 1, np.int64)
    starts[1:] = np.cumsum(counts)
    slot = np.arange(len(to_s)) - starts[to_s]
    neigh = np.zeros((n_pad, d_pad), np.int32)
    mask = np.zeros((n_pad, d_pad, n_sub), bool)
    neigh[to_s, slot] = from_s
    mask[to_s, slot, :] = w_s
    return neigh, mask, d_pad


def trim_shapes(n: int, d_in: int, d_out: int, p: int, use_rt: bool,
                use_proc: bool) -> tuple:
    """The compile bucket a trim run of these sizes lands in — shared
    by the runtime path and aot.precompile_elle_closure."""
    return (_round_up(_bucket(max(n, 2)), 128),
            _bucket(max(d_in, 4)), _bucket(max(d_out, 4)),
            max(8, _bucket(p + 1)), bool(use_rt), bool(use_proc))


def _cycle_from_core(dep: DepGraph, sub: frozenset) -> Optional[list]:
    """Host explanation once the device core is nonempty: the full
    oracle over explicit edges (the core guarantees a cycle exists, so
    this never runs on the valid-history hot path)."""
    return dep.find_cycle(types=set(sub))


def shape_bucket_for(g) -> dict:
    """The exact compile buckets a cycle search over `g` lands in, for
    every kernel the router might pick — the aot.precompile_elle_closure
    input. Mirrors the bucket derivation in trim_cycle_search /
    cycle_queries / cycle_queries_packed, so a warm call through the
    same lru caches leaves the real search at ZERO recompiles."""
    nodes = np.asarray(g.nodes)
    n = int(nodes.shape[0])
    edges = np.asarray(g.edges)
    typ = edges[:, 2] if len(edges) else np.zeros(0, np.int32)
    analytic = bool(getattr(g, "analytic", False))
    sm = np.isin(typ, [WW, WR, RW]) if analytic \
        else np.ones(len(typ), bool)
    n_pad = _round_up(max(_bucket(max(n, 2)), n + 2), 128)
    n_pad_trim = _round_up(_bucket(max(n, 2)), 128)
    e_to = edges[sm, 1] if len(edges) else np.zeros(0, np.int64)
    e_from = edges[sm, 0] if len(edges) else np.zeros(0, np.int64)
    d_in = int(np.bincount(
        np.searchsorted(nodes, e_to)).max()) if len(e_to) else 0
    d_out = int(np.bincount(
        np.searchsorted(nodes, e_from)).max()) if len(e_from) else 0
    use_rt = use_proc = False
    n_procs = 0
    if analytic:
        use_rt = bool((np.asarray(g.comp_evt) < 2 ** 60).any())
        proc = np.asarray(g.proc)
        use_proc = bool((proc >= 0).any())
        n_procs = int(proc.max()) + 1 if use_proc else 0
    n_rw = int(np.sum(typ == RW)) if len(typ) else 0
    trim = trim_shapes(n, _bucket(max(d_in, 4)),
                       _bucket(max(d_out, 4)), n_procs, use_rt,
                       use_proc)
    # the sharded bucket carries NO n_shards: the shard count is
    # resolved from the live fleet at warm/run time
    # (mesh.word_shard_count), so bucket derivation never queries
    # devices and the same plan record rewarming on a different fleet
    # width still lands on the executable that fleet can run
    return {"n": n,
            "trim": trim,
            "dense": {"n_pad": n_pad,
                      "e_pad": _bucket(max(len(edges), 1)),
                      "q_pad": _bucket(max(n_rw, 1)),
                      "iters": max(1, math.ceil(math.log2(n_pad)))},
            "sharded": {"n_pad": n_pad,
                        "q_pad": _bucket(max(n_rw, 1)),
                        "iters": max(1, math.ceil(math.log2(n_pad))),
                        "w": n_pad // 32}}


def trim_cycle_search(g, max_n: int = PACKED_MAX_N) -> Optional[dict]:
    """The full query battery on the trim kernel. `g` is a
    GraphTensors (builder mode: analytic interval jumps, only
    ww/wr/rw columns scatter) or a DepGraph (generic mode: every edge
    scatters). Returns the standard_cycle_search dict, or None over
    capacity.

    G0/G1c fire iff their subset core is nonempty. G-single/G2 anchor
    on rw edges; a cycle's nodes all survive trimming, so only rw
    edges with BOTH endpoints in the S2 core are candidates — zero
    for valid histories — and each candidate is settled by one host
    BFS over the allowed path types."""
    nodes = np.asarray(g.nodes)
    n = int(nodes.shape[0])
    if n > max_n:
        return None
    edges = np.asarray(g.edges)
    s0, s1, s2 = SUBSETS
    analytic = bool(getattr(g, "analytic", False))
    battery = {"G0": None, "G1c": None, "G-single": None, "G2": None}
    if n == 0 or not len(edges):
        return {**battery, "engine": "device",
                "util": {"kernel": "trim", "skipped": "empty-graph",
                         "kernel_s": 0.0}}

    id_of = {int(v): i for i, v in enumerate(nodes)}
    src = np.array([id_of[int(s)] for s in edges[:, 0]], np.int32)
    dst = np.array([id_of[int(d)] for d in edges[:, 1]], np.int32)
    typ = edges[:, 2]

    scatter_types = {WW, WR, RW} if analytic else None
    sm = np.isin(typ, list(scatter_types)) \
        if scatter_types is not None else np.ones(len(typ), bool)
    e_src, e_dst, e_typ = src[sm], dst[sm], typ[sm]
    n_sub = len(SUBSETS)
    w = np.zeros((len(e_src), n_sub), bool)
    for si, sub in enumerate(SUBSETS):
        w[:, si] = np.isin(e_typ, list(sub))

    use_rt = use_proc = False
    if analytic:
        inv_e = np.asarray(g.inv_evt)
        comp_e = np.asarray(g.comp_evt)
        proc = np.asarray(g.proc)
        ppos = np.asarray(g.proc_pos)
        use_rt = bool((comp_e < 2 ** 60).any())
        use_proc = bool((proc >= 0).any())
        n_procs = int(proc.max()) + 1 if use_proc else 0
    else:
        inv_e = comp_e = proc = ppos = None
        n_procs = 0

    n_pad = _round_up(_bucket(max(n, 2)), 128)
    in_neigh, in_mask, d_in_raw = _neighbor_pads(n_pad, e_src, e_dst, w)
    out_neigh, out_mask, d_out_raw = _neighbor_pads(n_pad, e_dst,
                                                    e_src, w)
    if in_neigh is None or out_neigh is None:
        return None  # degree past the gather bucket: dense kernels
    shapes = trim_shapes(n, d_in_raw, d_out_raw, n_procs, use_rt,
                         use_proc)
    n_pad, d_in, d_out, p_pad, _, _ = shapes
    BIGI = np.int32(2 ** 30)

    def pad(a, size, fill, dtype=np.int32):
        out = np.full(size, fill, dtype)
        out[:len(a)] = a
        return out

    if use_rt or use_proc:
        inv_p = pad(np.clip(inv_e, -BIGI, BIGI), n_pad, -BIGI)
        comp_p = pad(np.clip(comp_e, -BIGI, BIGI), n_pad, BIGI)
        proc_p = pad(np.where(proc < 0, p_pad - 1, proc), n_pad,
                     p_pad - 1)
        ppos_p = pad(ppos, n_pad, -1)
    else:
        inv_p = np.full(n_pad, -BIGI, np.int32)
        comp_p = np.full(n_pad, BIGI, np.int32)
        proc_p = np.full(n_pad, p_pad - 1, np.int32)
        ppos_p = np.full(n_pad, -1, np.int32)
    live0 = np.zeros((n_pad, n_sub), bool)
    live0[:n] = True

    kernel, compile_s = _compiled_trim(n_pad, d_in, d_out, n_sub,
                                       p_pad, use_rt, use_proc)

    import time as _t

    import jax

    from ..analysis import guards as _guards
    from .. import watchdog as _watchdog
    ins = (in_neigh, in_mask, out_neigh, out_mask,
           inv_p.astype(np.int32), comp_p.astype(np.int32), proc_p,
           ppos_p, live0)
    t0 = _t.monotonic()
    _guards.note_transfer("h2d",
                          sum(np.asarray(a).nbytes for a in ins),
                          what="elle-closure-inputs")
    wd = _watchdog.get_default()
    dm, dmark = _hbm_mark()
    with wd.watch("elle-closure", device="tpu", stall_s=300.0) as hb:
        wd.beat(hb, edges=int(len(e_src)), n=n, n_pad=n_pad,
                kernel="trim")
        live, counts, iters_run = kernel(*ins)
        jax.block_until_ready((live, counts, iters_run))
    kernel_s = _t.monotonic() - t0
    bodies = max(1, int(iters_run))
    iters_run = 2 * bodies  # two peel rounds per loop body
    counts = np.asarray(counts)[:min(bodies, 64)]
    live = np.asarray(live)[:n]
    _guards.note_transfer("d2h", live.nbytes + counts.nbytes,
                          what="elle-closure-outputs")
    core_sizes = [int(live[:, si].sum()) for si in range(n_sub)]
    util = {"kernel": "trim", "n_pad": n_pad,
            "d_in": d_in, "d_out": d_out,
            "edges": int(len(e_src)),
            "iters_run": iters_run,
            "kernel_s": round(kernel_s, 4),
            "compile_s": round(compile_s, 3),
            "iter_reach": [[int(v) for v in row] for row in counts],
            "converged_at": iters_run,
            "core_sizes": core_sizes,
            "reach_density": round(max(core_sizes) / max(n, 1), 6),
            "jumps": {"rt": use_rt, "proc": use_proc}}
    _hbm_close(util, dm, dmark)
    _record_closure(util, len(e_src), n)

    out: dict = {**battery, "engine": "device", "util": util}
    if not any(core_sizes):
        return out  # valid: the device core IS the verdict
    dep = g.to_depgraph() if hasattr(g, "to_depgraph") else g
    if core_sizes[0]:
        out["G0"] = _cycle_from_core(dep, s0)
    if core_sizes[1]:
        out["G1c"] = _cycle_from_core(dep, s1)
    if core_sizes[2]:
        # rw anchors with both endpoints in the S2 core
        core2 = {int(nodes[i]) for i in np.flatnonzero(live[:, 2])}
        adj1 = dep.adjacency(set(s1))
        adj2 = dep.adjacency(set(s2))
        for ei in np.flatnonzero(typ == RW):
            u, v = int(edges[ei, 0]), int(edges[ei, 1])
            if u not in core2 or v not in core2:
                continue
            if out["G-single"] is None:
                path = _bfs_path(adj1, v, u)
                if path is not None:
                    out["G-single"] = [u] + path
            if out["G2"] is None:
                path = _bfs_path(adj2, v, u)
                if path is not None:
                    out["G2"] = [u] + path
            if out["G-single"] is not None \
                    and out["G2"] is not None:
                break
    return out


# auto-routing's once-per-process device decision: a platform can be
# *configured* as an accelerator yet hang at init (this environment's
# site pin), so configuration alone must never route device-ward
_AUTO_DECISION: dict = {}


def _device_available(require_accel: bool = True) -> bool:
    """Can the auto path safely use the device backend? Requires a
    backend that PROVES it can initialize within a short bounded wait
    (util.backend_ready's shared daemon probe — a wedged init would
    otherwise hang this main-thread hot path). Only the POSITIVE
    verdict is cached: the first call pays the bounded wait, later
    calls re-check the probe's zero-cost fast path — so an init that
    completes after the first timeout upgrades auto-routing
    mid-process instead of pinning host forever. bench/dryrun force a
    device backend explicitly where the device plane must run.

    With require_accel=False (the trim kernel runs fine on the
    XLA cpu backend) a cpu platform qualifies too — only a missing
    jax or a wedged init rules the device plane out."""
    if _AUTO_DECISION.get("ok"):
        return True
    import importlib.util
    import os

    from ..util import backend_ready, safe_backend
    plat = safe_backend()
    if importlib.util.find_spec("jax") is None:
        return False
    if require_accel and (plat is None or plat == "cpu"):
        return False
    if _AUTO_DECISION.get("waited"):
        timeout = 0.05  # probe already running: just peek at it
    else:
        timeout = float(os.environ.get(
            "JEPSEN_TPU_ELLE_INIT_TIMEOUT_S", "10"))
        _AUTO_DECISION["waited"] = True
    ok = backend_ready(timeout)
    if ok:
        _AUTO_DECISION["ok"] = True
    return ok


def _squaring_select(n: int) -> tuple:
    """bf16 vs packed for one shape bucket, decided from the
    compiler's Lowered.cost_analysis bytes (the ops/adapt.py
    packed-table pattern: tracing+lowering only, no backend compile,
    cached per bucket by occupancy.cost_for). Past the bf16 capacity
    cap, packed is the only dense option; below it, packed wins when
    the bf16 closure's live working set stops fitting the HBM-comfort
    budget. Past PACKED_MAX_N no single chip holds the closure at
    all: the mesh-sharded column layout is selected when the fleet
    yields >= 2 word shards AND the analytic per-shard working set
    (gather buffer + 2/n_shards local blocks, cross-checked against
    the packed lowering's cost_analysis via occupancy.per_shard_cost)
    fits a chip's HBM; otherwise packed is returned so the caller's
    capacity check — and the host fallback behind it — fires."""
    import jax
    import jax.numpy as jnp

    from .. import occupancy as occupancy_mod
    from ..util import safe_backend

    if n > PACKED_MAX_N:
        from ..ops import aot as aot_mod
        from ..parallel.mesh import word_shard_count

        n_pad_s = _n_pad_for(n)
        ns = word_shard_count(n_pad_s // 32)
        bitset = len(SUBSETS) * float(n_pad_s) ** 2 / 8.0
        per_shard = bitset * (1.0 + 2.0 / ns)
        budget = getattr(aot_mod, "V5E_PEAK_HBM_BYTES", 1.6e10)
        c_pk = occupancy_mod.cost_cached(("elle-packed", n_pad_s))
        sel = {"n_shards": ns,
               "per_shard_bytes": int(per_shard),
               "gather_bytes_per_iter": int(bitset),
               "budget_bytes": int(budget),
               "cost_model": occupancy_mod.per_shard_cost(c_pk, ns)
               if c_pk else None}
        if n <= SHARDED_MAX_N and ns >= 2 and per_shard <= budget:
            sel["why"] = (f"n {n} > packed cap {PACKED_MAX_N}; "
                          f"{ns}-shard columns fit "
                          f"{per_shard:.2e} <= {budget:.2e}")
            return "sharded", sel
        sel["why"] = (f"n {n} over packed cap and sharded layout "
                      f"does not fit ({ns} shards, "
                      f"{per_shard:.2e} per shard)")
        return "packed", sel
    if n > DEFAULT_MAX_N:
        return "packed", {"why": f"n {n} > bf16 cap {DEFAULT_MAX_N}"}
    n_pad = _round_up(max(_bucket(n), n + 2), 128)
    iters = max(1, math.ceil(math.log2(n_pad)))

    def lower_bf16():
        dtype = jnp.bfloat16 if safe_backend() == "tpu" \
            else jnp.float32
        k = make_closure_kernel(n_pad, len(SUBSETS), iters, dtype)
        specs = (jax.ShapeDtypeStruct((128,), jnp.int32),
                 jax.ShapeDtypeStruct((128,), jnp.int32),
                 jax.ShapeDtypeStruct((len(SUBSETS), 128),
                                      jnp.float32),
                 jax.ShapeDtypeStruct((128,), jnp.int32),
                 jax.ShapeDtypeStruct((128,), jnp.int32))
        # lowering only (no backend compile), and occupancy.cost_for
        # caches the result per shape bucket
        return jax.jit(k).lower(*specs)  # jaxlint: ok(J003)

    def lower_packed():
        k = make_packed_closure_kernel(n_pad, len(SUBSETS), iters)
        specs = (jax.ShapeDtypeStruct(
            (len(SUBSETS), n_pad, n_pad // 32), jnp.uint32),
            jax.ShapeDtypeStruct((128,), jnp.int32),
            jax.ShapeDtypeStruct((128,), jnp.int32))
        return jax.jit(k).lower(*specs)  # jaxlint: ok(J003)

    c_bf = occupancy_mod.cost_for(("elle-bf16", n_pad), lower_bf16)
    c_pk = occupancy_mod.cost_for(("elle-packed", n_pad), lower_packed)
    sel = {"bytes_bf16": (c_bf or {}).get("bytes_accessed"),
           "bytes_packed": (c_pk or {}).get("bytes_accessed")}
    if c_bf and c_pk and c_bf["bytes_accessed"] > 0:
        # the MXU prefers bf16 until its working set stops fitting
        # comfortably: S live (N, N) bf16 planes + f32 product vs HBM
        from ..ops import aot as aot_mod
        budget = 0.25 * getattr(aot_mod, "V5E_PEAK_HBM_BYTES", 1.6e10)
        live = 3 * len(SUBSETS) * float(n_pad) ** 2 * 2
        if live > budget:
            sel["why"] = (f"bf16 live bytes {live:.2e} over "
                          f"{budget:.2e} budget")
            return "packed", sel
        sel["why"] = "bf16 working set fits; MXU squaring wins"
        return "bf16", sel
    sel["why"] = "cost analysis unavailable; bf16 under cap"
    return "bf16", sel


def device_cycle_search(g, max_n: int = PACKED_MAX_N,
                        kernel: Optional[str] = None) -> Optional[dict]:
    """The query battery on the device kernel family. Kernel choice
    per shape: `trim` wherever a dense squaring cannot pay for
    itself — always on a cpu/XLA backend (measured here: ONE squaring
    at n_pad 3072 costs ~0.5 s on one core; the whole trim fixpoint
    runs in tens of ms) — while an accelerator keeps the dense
    closures on the MXU/VPU with bf16-vs-packed-vs-sharded decided by
    Lowered.cost_analysis (`_squaring_select`; past PACKED_MAX_N the
    mesh-sharded column layout is the only dense option, and a
    sharded pick on a too-narrow fleet falls back to packed when n
    still fits one chip). Returns None over capacity."""
    from ..util import safe_backend

    n = int(np.asarray(g.nodes).shape[0])
    accel = safe_backend() not in (None, "cpu")
    if kernel is None:
        if accel:
            kernel, sel = _squaring_select(n)
        else:
            kernel = "trim"
            sel = {"why": "cpu backend: dense squaring is "
                          "compute-prohibitive; trim kernel"}
    else:
        sel = {"why": f"forced {kernel}"}

    if kernel == "trim":
        res = trim_cycle_search(g, max_n=min(max_n, PACKED_MAX_N))
        if res is not None:
            res["util"]["select"] = sel
            return res
        if not accel:
            # never fall through to a dense squaring on a cpu
            # backend: at trim-refusing sizes (degree past the gather
            # bucket, or n past capacity) the squaring costs minutes
            # per subset there — the host oracle is the right engine
            return None
        if n > PACKED_MAX_N:
            kernel, sel = "sharded", {"why": "over trim capacity; "
                                             "sharded columns"}
        else:
            kernel, sel = "packed", {"why": "over trim capacity"}

    s0, s1, s2 = SUBSETS
    # the dense kernels read only .nodes/.edges, which GraphTensors
    # provides directly — the labeled DepGraph materializes lazily
    # below, and only when something actually needs explaining
    if kernel == "sharded":
        qres = cycle_queries_sharded(
            g, max_n=max(max_n, SHARDED_MAX_N))
        if qres is None and n <= PACKED_MAX_N:
            # fleet too narrow to shard (< 2 word shards): the
            # single-chip packed kernel still covers this n
            kernel = "packed"
            sel = dict(sel,
                       fallback="sharded unavailable; packed covers n")
            qres = cycle_queries_packed(
                g, max_n=min(max_n, PACKED_MAX_N))
    elif kernel == "bf16":
        qres = cycle_queries(g, max_n=min(max_n, DEFAULT_MAX_N))
    else:
        qres = cycle_queries_packed(g, max_n=min(max_n, PACKED_MAX_N))
    if qres is None:
        return None
    out = {"engine": "device", "util": dict(qres["util"])}
    out["util"].setdefault("kernel", kernel)
    out["util"]["select"] = sel
    hits = (any(qres["sccs"][si] for si in range(len(SUBSETS)))
            or bool(np.asarray(qres["rw_closed"]).any()))
    dep = (g.to_depgraph() if hits and hasattr(g, "to_depgraph")
           else g)
    for name, si, sub in (("G0", 0, s0), ("G1c", 1, s1)):
        cyc = None
        if hits:
            for comp in qres["sccs"][si]:
                cyc = dep._cycle_in(set(comp), set(sub))
                if cyc:
                    break
        out[name] = cyc
    out["G-single"] = _first_closed(dep, qres, 1, set(s1)) \
        if hits else None
    out["G2"] = _first_closed(dep, qres, 2, set(s2)) if hits else None
    return out


def standard_cycle_search(g, backend: str = "host",
                          max_n: int = DEFAULT_MAX_N) -> dict:
    """The four-query battery both elle checkers run, on any engine.
    `g` is a DepGraph or an elle/build.py GraphTensors. Returns
    {"G0": cycle|None, "G1c": ..., "G-single": ..., "G2": ...} where
    each cycle is a node list [a, ..., a]; device verdicts are
    re-derived into concrete cycles host-side, restricted to the
    flagged component/edge ("device decides, host explains").

    backend:
      "host"    Tarjan + per-edge BFS oracle (and the explainer).
      "tpu"     the original bf16 dense closure, engine "tpu" —
                kept verbatim as the MULTICHIP evidence path.
      "packed"  the uint32 bitset closure (capacity PACKED_MAX_N).
      "sharded" the mesh-sharded bitset closure: word columns split
                across the "words" device axis, capacity
                SHARDED_MAX_N (falls back to packed when the fleet
                yields < 2 shards and n still fits one chip).
      "trim"    the peel-to-core trim kernel.
      "device"  kernel picked per shape (device_cycle_search).
      "auto"    ops/route.elle_cycle_route decides host vs device
                from (n, e, rw) shape stats; the decision is
                recorded as `route_reason`.

    The "engine" key records what actually ran ("tpu", "device",
    "trim", "packed", "host", or "host-fallback" when a device
    request exceeded capacity); device results carry util.kernel."""
    s0, s1, s2 = SUBSETS
    engine = backend
    route_reason = None
    if backend == "auto":
        from ..ops.route import elle_cycle_route
        from ..util import safe_backend
        edges = np.asarray(g.edges)
        rw = int(np.sum(edges[:, 2] == RW)) if len(edges) else 0
        plat = safe_backend()
        accel = plat not in (None, "cpu")
        n_route = int(np.asarray(g.nodes).shape[0])
        ns_route = 0
        if accel:
            try:
                import jax

                from ..parallel.mesh import word_shard_count
                ns_route = word_shard_count(
                    _n_pad_for(n_route) // 32, len(jax.devices()))
            except Exception:  # noqa: BLE001 — no fleet, no shards
                ns_route = 0
        backend, route_reason = elle_cycle_route(
            n=n_route, e=int(len(edges)),
            rw_edges=rw, accel=accel,
            device_ok=_device_available(require_accel=accel),
            packed_cap=PACKED_MAX_N, sharded_cap=SHARDED_MAX_N,
            n_shards=ns_route)
        engine = backend
    if backend == "device":
        res = device_cycle_search(g, max_n=max(max_n, SHARDED_MAX_N))
        if res is None:
            backend = engine = "host-fallback"  # over capacity
        else:
            if route_reason:
                res["route_reason"] = route_reason
            return res
    if backend in ("trim", "packed", "sharded"):
        res = device_cycle_search(g, max_n=max(max_n, SHARDED_MAX_N),
                                  kernel=backend)
        if res is None:
            backend = engine = "host-fallback"
        else:
            # a forced trim request can still fall through to packed
            # (degree past the gather bucket on an accelerator), and
            # a sharded request to packed (fleet too narrow) — only
            # claim the forced engine when it actually ran
            if res["util"].get("kernel", backend) == backend:
                res["engine"] = backend
            if route_reason:
                res["route_reason"] = route_reason
            return res
    if backend == "tpu":
        dep = g.to_depgraph() if hasattr(g, "to_depgraph") else g
        res = cycle_queries(dep, max_n=max_n)
        if res is None:
            backend = engine = "host-fallback"  # over capacity
        else:
            out = {"engine": "tpu", "util": res["util"]}
            for name, si, sub in (("G0", 0, s0), ("G1c", 1, s1)):
                cyc = None
                for comp in res["sccs"][si]:
                    cyc = dep._cycle_in(set(comp), set(sub))
                    if cyc:
                        break
                out[name] = cyc
            # G-single: rw edge closed by a NON-rw path (subset 1);
            # G2: closed by any path (subset 2)
            out["G-single"] = _first_closed(dep, res, 1, set(s1))
            out["G2"] = _first_closed(dep, res, 2, set(s2))
            return out
    if backend not in ("host", "host-fallback"):
        raise ValueError(f"unknown backend {backend!r}")
    dep = g.to_depgraph() if hasattr(g, "to_depgraph") else g
    out = {
        "engine": engine,
        "G0": dep.find_cycle(types=set(s0)),
        "G1c": dep.find_cycle(types=set(s1)),
        "G-single": dep.find_cycle_with(RW, set(s1),
                                        exactly_one=True),
        "G2": dep.find_cycle_with(RW, set(s1), exactly_one=False),
    }
    if route_reason:
        out["route_reason"] = route_reason
    return out


def _first_closed(g: DepGraph, res: dict, subset_idx: int,
                  path_types: set) -> Optional[list]:
    """Host re-derivation: for the first device-flagged rw edge, the
    concrete closing path (BFS over path_types, one edge's worth of
    work)."""
    from .graph import _bfs_path
    closed = res["rw_closed"][subset_idx]
    adj = g.adjacency(path_types - {RW}) if subset_idx == 1 \
        else g.adjacency(path_types)
    for ei, (s, d) in enumerate(res["rw_edges"]):
        if not closed[ei]:
            continue
        path = _bfs_path(adj, d, s)
        if path is not None:
            return [s] + path
    return None
