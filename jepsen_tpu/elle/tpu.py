"""The TPU Elle plane: cycle detection as dense boolean linear algebra.

The reference's Elle (dependency-graph cycle search over txn histories,
wrapped at jepsen/src/jepsen/tests/cycle/append.clj:11-22 and wr.clj:
14-53) walks graphs with DFS on the JVM. SURVEY.md flags it as the
phase-2 TPU target: "SCC/cycle detection as sparse matrix ops". This
module is that pass, designed MXU-first rather than as a graph-walk
translation:

  adjacency  A[s]        one (N, N) 0/1 matrix per edge-type subset s
                         (G0 wants ww-only, G1c ww+wr, G2 adds rw),
                         scattered from the DepGraph's (E, 3) edge
                         columns in one indexed update — the subsets
                         ride a leading batch axis, so all closures
                         compute in lockstep.
  closure    R = (A|I)^(2^k)   repeated squaring under lax.fori_loop:
                         ceil(log2(N)) batched matmuls, each a bf16
                         (N, N) @ (N, N) on the MXU with f32
                         accumulation, re-binarized after every step.
                         Static iteration count — no data-dependent
                         control flow, one compile per shape bucket.
  SCCs       mutual = R & R^T; label[i] = min{j : mutual[i, j]}
                         a nontrivial SCC exists iff label != arange.
  rw queries G-single / G2 ask "is some rw edge (s, d) closed by a
                         path d -> s?" — per-edge BFS on the host
                         (O(rw_edges * E), the host path's hot spot),
                         but a single gather R[:, dst, src] here.

Verdicts come off the device; *explanations* stay on the host: when a
query fires, the caller re-derives the concrete cycle by BFS restricted
to the flagged component / edge, which is tiny. This mirrors the WGL
split (device decides, host explains counterexamples).

bf16 safety: matmul entries count paths (up to N); bf16 rounds integers
above 256, but every addend is >= 0 and rounding is to-nearest, so a
positive sum can never round to zero — and only (sum > 0) is consumed.

Capacity: dense (S, N, N) closure is the right trade below ~8k txns.
At the 8192 cap each bf16 subset matrix is 8192^2 * 2 B = 128 MiB, and
the kernel holds S=3 of them plus the f32 einsum product and the
mutual/transpose temporaries — peak live bytes ~1 GiB, comfortably
inside a v5e's 16 GiB HBM. One squaring is ~2 * 3 * 8192^3 flops
=~ 3.3 TFLOP across the batch, ~17 ms at v5e bf16 peak (197 TFLOP/s).
Histories past the cap — BASELINE's independent configs shard per key
long before that — fall back to the host oracle, recorded in the
result.
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import Optional, Sequence

import numpy as np

from .graph import PROCESS, REALTIME, RW, WR, WW, DepGraph

# The standard Elle query battery (append.clj / wr.clj semantics).
# Subsets are cumulative: S0 (G0) < S1 (G1c, and the G-single closure)
# < S2 (the G2 closure).
SUBSETS = (
    frozenset({WW, REALTIME, PROCESS}),
    frozenset({WW, WR, REALTIME, PROCESS}),
    frozenset({WW, WR, RW, REALTIME, PROCESS}),
)

DEFAULT_MAX_N = 8192


def _round_up(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def _bucket(n: int) -> int:
    """Next power of two, so jit recompiles stay logarithmic in size."""
    return max(1, 1 << (int(n) - 1).bit_length())


def make_closure_kernel(n_pad: int, n_sub: int, iters: int, dtype):
    """The closure-by-squaring kernel as a plain traceable function —
    shared by the runtime path below and the AOT TPU-evidence path
    (ops/aot.py), which lowers it for a v5e topology in bf16."""
    import jax
    import jax.numpy as jnp

    def kernel(src, dst, w, q_src, q_dst):
        # adjacency per subset: (S, N, N); padded edges carry w == 0
        adj = jnp.zeros((n_sub, n_pad, n_pad), dtype)
        adj = adj.at[:, src, dst].max(w.astype(dtype))
        eye = jnp.eye(n_pad, dtype=dtype)
        reach = jnp.maximum(adj, eye[None])

        # per-iteration frontier of the label propagation: reachable
        # pair count per subset after each squaring — the closure's
        # occupancy counters, returned with the verdict outputs so
        # they ride the SAME device->host fetch (no extra transfer,
        # doc/OBSERVABILITY.md "Occupancy & roofline")
        counts0 = jnp.zeros((iters, n_sub), jnp.int32)

        # Convergence early-exit (ROADMAP item 2's reclaimable
        # squarings, exposed by PR 8's converged_at counters): reach
        # under repeated squaring is monotone and idempotent at the
        # fixed point, so once the per-subset pair counts repeat the
        # remaining scheduled squarings are pure wasted MXU work —
        # stop there. Outputs are bit-identical to the fixed
        # schedule; `iters_run` reports what actually executed.
        def cond(st):
            _, _, i, changed = st
            return (i < iters) & changed

        def square(st):
            r, cnt, i, _ = st
            prod = jnp.einsum("sij,sjk->sik", r, r,
                              preferred_element_type=jnp.float32)
            r2 = (prod > 0).astype(dtype)
            c = jnp.sum((r2 > 0).astype(jnp.int32), axis=(1, 2))
            prev = jnp.where(i > 0, cnt[jnp.maximum(i - 1, 0)],
                             jnp.full((n_sub,), -1, jnp.int32))
            cnt = cnt.at[i].set(c)
            return r2, cnt, i + 1, jnp.any(c != prev)

        reach, counts, iters_run, _ = jax.lax.while_loop(
            cond, square, (reach, counts0, jnp.int32(0),
                           jnp.asarray(True)))
        rb = reach > 0
        mutual = rb & jnp.swapaxes(rb, 1, 2)
        cols = jnp.arange(n_pad, dtype=jnp.int32)
        labels = jnp.where(mutual, cols[None, None, :],
                           n_pad).min(axis=2)
        # rw-closure queries: path q_dst -> q_src under each subset
        closed = rb[:, q_dst, q_src]
        return labels.astype(jnp.int32), closed, counts, iters_run

    return kernel


@lru_cache(maxsize=32)
def _compiled(n_pad: int, e_pad: int, q_pad: int, n_sub: int,
              iters: int):
    """The closure kernel for one shape bucket, AOT-compiled so the
    compile cost is measured here (once per bucket) and callers time
    pure execution — no double-run for telemetry. Returns
    (compiled_fn, compile_s)."""
    import time as _t

    import jax
    import jax.numpy as jnp

    from ..util import safe_backend

    # lock-free platform probe: jax.default_backend() would trigger
    # backend init itself, ahead of the bounded-wait policy upstream
    dtype = jnp.bfloat16 if safe_backend() == "tpu" else jnp.float32
    kernel = make_closure_kernel(n_pad, n_sub, iters, dtype)

    specs = (jax.ShapeDtypeStruct((e_pad,), jnp.int32),
             jax.ShapeDtypeStruct((e_pad,), jnp.int32),
             jax.ShapeDtypeStruct((n_sub, e_pad), jnp.float32),
             jax.ShapeDtypeStruct((q_pad,), jnp.int32),
             jax.ShapeDtypeStruct((q_pad,), jnp.int32))
    t0 = _t.monotonic()
    compiled = jax.jit(kernel).lower(*specs).compile()
    return compiled, _t.monotonic() - t0


def cycle_queries(g: DepGraph,
                  subsets: Sequence[frozenset] = SUBSETS,
                  rw_type: int = RW,
                  max_n: int = DEFAULT_MAX_N) -> Optional[dict]:
    """Run the batched closure over `subsets` and the rw-closure
    queries on the device. Returns
      {"sccs": [per-subset list of >1-node components (history ids)],
       "rw_edges": [(src, dst) history ids],
       "rw_closed": (S, n_rw) bool — rw edge closed under subset s}
    or None when the graph exceeds max_n (caller falls back to host).
    """
    nodes = g.nodes
    n = int(nodes.shape[0])
    if n > max_n:
        return None
    edges = g.edges
    id_of = {int(v): i for i, v in enumerate(nodes)}

    # padding nodes are isolated; n_pad >= n + 2 guarantees two distinct
    # isolated nodes for the padded (always-False) rw queries
    n_pad = _round_up(max(_bucket(n), n + 2), 128)
    src = np.array([id_of[int(s)] for s in edges[:, 0]], np.int32)
    dst = np.array([id_of[int(d)] for d in edges[:, 1]], np.int32)
    typ = edges[:, 2]
    n_sub = len(subsets)
    w = np.zeros((n_sub, len(src)), np.float32)
    for si, sub in enumerate(subsets):
        w[si] = np.isin(typ, list(sub)).astype(np.float32)

    rw_mask = typ == rw_type
    q_src, q_dst = src[rw_mask], dst[rw_mask]
    rw_edges = [(int(edges[i, 0]), int(edges[i, 1]))
                for i in np.flatnonzero(rw_mask)]

    e_pad = _bucket(max(len(src), 1))
    q_pad = _bucket(max(len(q_src), 1))

    def pad(a, size, fill):
        out = np.full(size, fill, a.dtype if len(a) else np.int32)
        out[:len(a)] = a
        return out

    src_p = pad(src, e_pad, 0)
    dst_p = pad(dst, e_pad, 0)
    w_p = np.zeros((n_sub, e_pad), np.float32)
    w_p[:, :w.shape[1]] = w
    # padded queries land on distinct isolated padding nodes -> False
    q_src_p = pad(q_src, q_pad, n_pad - 1)
    q_dst_p = pad(q_dst, q_pad, n_pad - 2)

    iters = max(1, math.ceil(math.log2(n_pad)))
    kernel, compile_s = _compiled(n_pad, e_pad, q_pad, n_sub, iters)
    import time as _t

    import jax

    from ..analysis import guards as _guards
    from .. import watchdog as _watchdog
    t0 = _t.monotonic()
    ins = (np.asarray(src_p, np.int32), np.asarray(dst_p, np.int32),
           np.asarray(w_p, np.float32), np.asarray(q_src_p, np.int32),
           np.asarray(q_dst_p, np.int32))
    _guards.note_transfer("h2d", sum(a.nbytes for a in ins),
                          what="elle-closure-inputs")
    # watchdog coverage for the one blocking device call here: the
    # closure kernel has no poll loop to heartbeat from, so the beat
    # lands just before the call — a hung MXU dispatch leaves the
    # source beating-silent and the monitor flags it (doc/
    # OBSERVABILITY.md "stall watchdog")
    wd = _watchdog.get_default()
    # stall_s override: the closure at capacity is a known-slow
    # healthy call (BENCH_r04: ~57 s of dense f32 matmuls on cpu) —
    # only a multi-minute silence is a hang here
    with wd.watch("elle-closure", device="tpu",
                  stall_s=300.0) as hb:
        wd.beat(hb, edges=int(len(src)), n=n, n_pad=n_pad, iters=iters)
        labels, closed, iter_counts, iters_run = kernel(*ins)
        jax.block_until_ready((labels, closed, iter_counts, iters_run))
    kernel_s = _t.monotonic() - t0
    # Convergence early-exit (make_closure_kernel): the device loop
    # stopped after `iters_run` squarings; the rest of the fixed
    # schedule is reclaimed MXU work, reported below.
    iters_run = max(1, int(iters_run))
    # Achieved matmul throughput vs the flop model in the module
    # docstring: iters_run squarings x n_sub batched (n_pad)^3
    # matmuls — the work that actually executed.
    flops = 2.0 * n_sub * iters_run * float(n_pad) ** 3
    # per-iteration frontier (occupancy plane): reachable-pair counts
    # per subset after each executed squaring, and the first
    # iteration at which the widest subset's closure stopped growing
    iter_counts = np.asarray(iter_counts)[:iters_run]  # (run, n_sub)
    iter_reach = [[int(v) for v in row] for row in iter_counts]
    widest = iter_counts[:, -1]
    converged_at = int(iters_run)
    for i in range(1, iters_run):
        if widest[i] == widest[i - 1]:
            converged_at = i
            break
    util = {"n_pad": n_pad, "iters": iters,
            "iters_run": iters_run,
            "iters_reclaimed": int(iters) - iters_run,
            "kernel_s": round(kernel_s, 4),
            "compile_s": round(compile_s, 3),
            "achieved_tflops": round(flops / 1e12 / max(kernel_s, 1e-9),
                                     2),
            "iter_reach": iter_reach,
            "converged_at": converged_at,
            "reach_density": round(
                float(widest[-1]) / float(n_pad) ** 2, 6)}
    from .. import metrics as _metrics
    mx = _metrics.get_default()
    if mx.enabled:
        # the MXU plane's telemetry rides the same registry as the
        # search kernels' (doc/OBSERVABILITY.md)
        mx.series("elle_closure",
                  "per-call Elle closure-kernel telemetry").append(
            {"edges": int(len(src)), "n": n, **util})
        mx.counter("elle_closure_calls_total",
                   "batched closure kernel invocations").inc()
        mx.histogram("elle_closure_seconds",
                     "closure kernel wall (post-compile)").observe(
            kernel_s)
    labels = np.asarray(labels)[:, :n]
    closed = np.asarray(closed)[:, :len(rw_edges)]
    _guards.note_transfer("d2h",
                          labels.nbytes + closed.nbytes
                          + iter_counts.nbytes,
                          what="elle-closure-outputs")

    sccs: list = []
    for si in range(n_sub):
        comps: dict = {}
        for i in range(n):
            lab = int(labels[si, i])
            if lab != i:
                comps.setdefault(lab, [int(nodes[lab])]).append(
                    int(nodes[i]))
        sccs.append([sorted(c) for c in comps.values()])
    return {"sccs": sccs, "rw_edges": rw_edges, "rw_closed": closed,
            "util": util}


# auto-routing's once-per-process device decision: a platform can be
# *configured* as an accelerator yet hang at init (this environment's
# site pin), so configuration alone must never route device-ward
_AUTO_DECISION: dict = {}


def _device_available() -> bool:
    """Can the auto path safely use the device backend? Requires a
    non-cpu platform AND a backend that PROVES it can initialize
    within a short bounded wait (util.backend_ready's shared daemon
    probe — a wedged init would otherwise hang this main-thread hot
    path). Only the POSITIVE verdict is cached: the first call pays
    the bounded wait, later calls re-check the probe's zero-cost fast
    path — so an init that completes after the first timeout upgrades
    auto-routing mid-process instead of pinning host forever.
    bench/dryrun force backend="tpu" explicitly where the device
    plane must run."""
    if _AUTO_DECISION.get("ok"):
        return True
    import importlib.util
    import os

    from ..util import backend_ready, safe_backend
    plat = safe_backend()
    if plat is None or plat == "cpu" \
            or importlib.util.find_spec("jax") is None:
        return False
    if _AUTO_DECISION.get("waited"):
        timeout = 0.05  # probe already running: just peek at it
    else:
        timeout = float(os.environ.get(
            "JEPSEN_TPU_ELLE_INIT_TIMEOUT_S", "10"))
        _AUTO_DECISION["waited"] = True
    ok = backend_ready(timeout)
    if ok:
        _AUTO_DECISION["ok"] = True
    return ok


def standard_cycle_search(g: DepGraph, backend: str = "host",
                          max_n: int = DEFAULT_MAX_N) -> dict:
    """The four-query battery both elle checkers run, on either
    backend. Returns {"G0": cycle|None, "G1c": ..., "G-single": ...,
    "G2": ...} where each cycle is a node list [a, ..., a]. Device
    verdicts are re-derived into concrete cycles host-side, restricted
    to the flagged component/edge.

    backend: "host" (Tarjan + per-edge BFS oracle), "tpu" (batched
    closure kernel), or "auto" (tpu when the graph is big enough that
    the O(rw_edges * E) host queries hurt, else host).

    The "engine" key records which backend actually ran ("tpu",
    "host", or "host-fallback" when a tpu request exceeded max_n)."""
    s0, s1, s2 = SUBSETS
    engine = backend
    if backend == "auto":
        # The dense closure only pays off on a real accelerator: 12
        # squarings of (4096)^3 matmuls are milliseconds on the MXU but
        # minutes on a CPU host, where Tarjan wins at any size.
        backend = "tpu" if (_device_available()
                            and len(g.nodes) >= 512
                            and len(g) >= 512) else "host"
        engine = backend
    if backend == "tpu":
        res = cycle_queries(g, max_n=max_n)
        if res is None:
            backend = engine = "host-fallback"  # over capacity
        else:
            out: dict = {"engine": "tpu", "util": res["util"]}
            for name, si, sub in (("G0", 0, s0), ("G1c", 1, s1)):
                cyc = None
                for comp in res["sccs"][si]:
                    cyc = g._cycle_in(set(comp), set(sub))
                    if cyc:
                        break
                out[name] = cyc
            # G-single: rw edge closed by a NON-rw path (subset 1);
            # G2: closed by any path (subset 2)
            out["G-single"] = _first_closed(g, res, 1, set(s1))
            out["G2"] = _first_closed(g, res, 2, set(s2))
            return out
    if backend not in ("host", "host-fallback"):
        raise ValueError(f"unknown backend {backend!r}")
    return {
        "engine": engine,
        "G0": g.find_cycle(types=set(s0)),
        "G1c": g.find_cycle(types=set(s1)),
        "G-single": g.find_cycle_with(RW, set(s1), exactly_one=True),
        "G2": g.find_cycle_with(RW, set(s1), exactly_one=False),
    }


def _first_closed(g: DepGraph, res: dict, subset_idx: int,
                  path_types: set) -> Optional[list]:
    """Host re-derivation: for the first device-flagged rw edge, the
    concrete closing path (BFS over path_types, one edge's worth of
    work)."""
    from .graph import _bfs_path
    closed = res["rw_closed"][subset_idx]
    adj = g.adjacency(path_types - {RW}) if subset_idx == 1 \
        else g.adjacency(path_types)
    for ei, (s, d) in enumerate(res["rw_edges"]):
        if not closed[ei]:
            continue
        path = _bfs_path(adj, d, s)
        if path is not None:
            return [s] + path
    return None
