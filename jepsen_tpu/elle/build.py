"""Tensorized Elle graph construction: history -> edge columns.

The host builders in `append.py` / `wr.py` / `graph.py` walk txn
micro-ops with Python dict loops — fine for correctness (they remain
the oracle and the explanation path), but they put an O(ops x mops x
read-list) interpreter bill in front of every cycle search. This
module re-derives the SAME graphs as flat numpy columns, following the
`ops/encode.py` idiom (host-side encode, fixed dtype columns, interned
alphabets):

  encode     every micro-op becomes rows in struct-of-arrays form:
             append/write rows (txn, key, value), read rows (txn, key,
             length), read-ELEMENT rows (read, position, value) — list
             reads explode into one row per observed element, which is
             what makes version-order checks vectorizable.
  intern     keys and (key, value) pairs get dense int32 ids
             (`_hashable` from ops/encode.py); the id->object table
             reconstructs the dict forms the host anomaly passes use.
  derive     writer index, version orders, and the ww/wr/rw edge lists
             come out of sorts/segment ops over those columns; the
             realtime sweep in graph.realtime_graph collapses into a
             frontier-interval formula (see `realtime_arrays`) and the
             process graph into one lexsort.

Parity contract: for every history the derived `(E, 3)` edge columns
equal the host DepGraph's edge set exactly (same dedup, same dropped
self-edges), and the writer/orders dicts reconstruct to the same
values — tests/test_elle_build.py holds both, including aborted/info
txns and G1a/G1b corpora. Order-dependent anomaly *payloads*
(duplicate-elements, incompatible-order) are the one place vectorized
re-derivation would drift, so dirty histories take the exact host loop
for those passes (`builder: "host-fallback"` in telemetry); the clean
common case never does.

The product, `GraphTensors`, is what the device cycle engines consume
directly — nodes, edge columns, and the analytic interval metadata
(`inv_evt`/`comp_evt` event positions, process chain positions) that
lets the propagation kernel apply realtime/process reachability as
O(N) interval jumps instead of materialized O(N^2) edges. No DepGraph
is built on the hot path; `to_depgraph()` re-runs the host builders
lazily for the host engine and for cycle explanations ("device
decides, host explains").
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

from ..txn import APPEND, R, W
from ..history import History
from ..ops.encode import _hashable
from .graph import PROCESS, REALTIME, RW, WR, WW, DepGraph

_BIG = np.int64(2**62)


class BuildUnsupported(Exception):
    """The history cannot be tensorized (e.g. ops without comparable
    times); callers fall back to the host builders."""


class Interner:
    """Hashable objects -> dense int32 ids, with the inverse table."""

    def __init__(self):
        self._ids: dict = {}
        self.objects: list = []

    def add(self, obj) -> int:
        key = _hashable(obj)
        i = self._ids.get(key)
        if i is None:
            i = len(self.objects)
            self._ids[key] = i
            self.objects.append(obj)
        return i

    def get(self, obj) -> Optional[int]:
        return self._ids.get(_hashable(obj))

    def __len__(self) -> int:
        return len(self.objects)


@dataclass
class GraphTensors:
    """A typed txn digraph in the columnar layout the device cycle
    engines consume, plus the interval metadata for analytic
    realtime/process jumps. Node references in `edges` are HISTORY
    indices, like DepGraph's."""

    nodes: np.ndarray                 # (T,) int32 sorted history indices
    edges: np.ndarray                 # (E, 3) int32 (src, dst, typ)
    # analytic-jump metadata, aligned with `nodes` (local ids):
    inv_evt: Optional[np.ndarray] = None   # (T,) int64; -_BIG absent
    comp_evt: Optional[np.ndarray] = None  # (T,) int64; +_BIG absent
    proc: Optional[np.ndarray] = None      # (T,) int32; -1 absent
    proc_pos: Optional[np.ndarray] = None  # (T,) int32; -1 absent
    # True when every REALTIME/PROCESS edge in `edges` is exactly the
    # reduced form of the interval relations above, so a closure
    # engine may replace those edges with interval jumps:
    analytic: bool = False
    build_s: float = 0.0
    builder: str = "tensor"           # "tensor" | "host-fallback"
    _explain: Optional[Callable[[], DepGraph]] = None
    _dep: Optional[DepGraph] = field(default=None, repr=False)

    def __len__(self) -> int:
        return int(self.edges.shape[0])

    def counts(self) -> dict:
        typ = self.edges[:, 2]
        from .graph import EDGE_NAMES
        return {EDGE_NAMES[t]: int(np.sum(typ == t))
                for t in np.unique(typ)} if len(typ) else {}

    def to_depgraph(self) -> DepGraph:
        """The labeled host DepGraph — built lazily by re-running the
        host builders (the explanation/oracle path), cached."""
        if self._dep is None:
            if self._explain is not None:
                self._dep = self._explain()
            else:
                g = DepGraph()
                for n in self.nodes:
                    g.add_node(int(n))
                for s, d, t in self.edges:
                    g.add_edge(int(s), int(d), int(t))
                self._dep = g
        return self._dep


def _dedup_edges(parts: list) -> np.ndarray:
    """Concatenate (E_i, 3) parts, drop self-edges, dedup rows —
    DepGraph.add_edge semantics as one unique() call."""
    parts = [np.asarray(p, np.int32).reshape(-1, 3) for p in parts
             if p is not None and len(p)]
    if not parts:
        return np.zeros((0, 3), np.int32)
    e = np.concatenate(parts, axis=0)
    e = e[e[:, 0] != e[:, 1]]
    if not len(e):
        return e
    return np.unique(e, axis=0)


def _times_ok(ops) -> bool:
    return all(isinstance(op.time, int) for op in ops)


# -- realtime / process graphs, vectorized -----------------------------------

def realtime_arrays(history: History):
    """The reduced realtime graph of graph.realtime_graph, derived
    without the sweep.

    Event positions order all invocations/completions exactly as the
    host sweep does (time, completions-first, stable). An op A sits in
    the frontier for the event interval (comp_evt(A), s(A)) where
      s(A) = min{ comp_evt(B) : inv_evt(B) > comp_evt(A) }
    — the first completion of an op invoked after A completed is what
    supersedes A. D's predecessors are then exactly the A with
    comp_evt(A) < inv_evt(D) < s(A): one searchsorted range per A,
    expanded into edge rows. Transitive closure of these reduced edges
    equals the full interval relation comp_evt(A) < inv_evt(B), which
    is what the analytic jump in the propagation kernel applies.

    Returns (idx (P,) i32, inv_evt (P,) i64, comp_evt (P,) i64,
    edges (E, 2) i32) over the ok-completed pairs."""
    pairs = [(inv, comp) for inv, comp in history.pairs()
             if comp is not None and comp.is_ok]
    P = len(pairs)
    if P == 0:
        z = np.zeros(0, np.int64)
        return (np.zeros(0, np.int32), z, z,
                np.zeros((0, 2), np.int32))
    if not _times_ok([p[0] for p in pairs] + [p[1] for p in pairs]):
        raise BuildUnsupported("non-integer op times")
    idx = np.asarray([c.index for _i, c in pairs], np.int32)
    inv_t = np.asarray([i.time for i, _c in pairs], np.int64)
    comp_t = np.asarray([c.time for _i, c in pairs], np.int64)

    # event positions: primary time, completions (kind 0) before
    # invocations (kind 1) at equal times, stable in pair order —
    # the host sweep's exact sort key
    ev_time = np.concatenate([inv_t, comp_t])
    ev_kind = np.concatenate([np.ones(P, np.int8), np.zeros(P, np.int8)])
    order = np.lexsort((ev_kind, ev_time))  # stable: ties by position
    pos = np.empty(2 * P, np.int64)
    pos[order] = np.arange(2 * P)
    inv_evt, comp_evt = pos[:P], pos[P:]

    # s(A) = min{comp_evt(B) : inv_evt(B) > comp_evt(A)} over ops
    # that CAN supersede: in the sweep, removal applies preds_of[B]
    # (the frontier snapshot at B's invocation) at B's COMPLETION —
    # an op whose completion event precedes its own invocation (a
    # zero-duration op; completions sort first at equal times) has an
    # empty snapshot when it completes and removes nothing, itself
    # included. So only ops with inv_evt < comp_evt supersede.
    normal = inv_evt < comp_evt
    inv_n = inv_evt[normal]
    comp_n = comp_evt[normal]
    by_inv_n = np.argsort(inv_n, kind="stable")
    inv_n_sorted = inv_n[by_inv_n]
    comp_by_inv = comp_n[by_inv_n]
    Pn = len(inv_n)
    sufmin = np.full(Pn + 1, _BIG, np.int64)
    if Pn:
        sufmin[:Pn] = np.minimum.accumulate(comp_by_inv[::-1])[::-1]
    s_a = sufmin[np.searchsorted(inv_n_sorted, comp_evt,
                                 side="right")]

    # D's with inv_evt in (comp_evt(A), s(A)): a range per A over ALL
    # ops (zero-duration ops still receive predecessor edges)
    by_inv = np.argsort(inv_evt, kind="stable")
    inv_sorted = inv_evt[by_inv]
    lo = np.searchsorted(inv_sorted, comp_evt, side="right")
    hi = np.searchsorted(inv_sorted, s_a, side="left")
    counts = np.maximum(hi - lo, 0)
    total = int(counts.sum())
    if total == 0:
        return idx, inv_evt, comp_evt, np.zeros((0, 2), np.int32)
    src_rep = np.repeat(np.arange(P), counts)
    offs = np.arange(total) - np.repeat(
        np.concatenate([[0], np.cumsum(counts)[:-1]]), counts)
    dst_rank = np.repeat(lo, counts) + offs
    dst_rep = by_inv[dst_rank]
    keep = src_rep != dst_rep
    edges = np.stack([idx[src_rep[keep]], idx[dst_rep[keep]]], axis=1)
    return idx, inv_evt, comp_evt, edges.astype(np.int32)


def process_arrays(history: History):
    """graph.process_graph as columns: per-process chains of
    ok-completed ops in pairs order. Returns (idx (P,) i32,
    proc_id (P,) i32, chain_pos (P,) i32, edges (E, 2) i32)."""
    rows = [(inv.process, comp.index) for inv, comp in history.pairs()
            if comp is not None and comp.is_ok]
    P = len(rows)
    if P == 0:
        z = np.zeros(0, np.int32)
        return z, z, z, np.zeros((0, 2), np.int32)
    procs = Interner()
    pid = np.asarray([procs.add(p) for p, _ in rows], np.int32)
    idx = np.asarray([i for _, i in rows], np.int32)
    order = np.lexsort((np.arange(P), pid))  # stable within process
    pid_s, idx_s = pid[order], idx[order]
    same = np.flatnonzero(pid_s[1:] == pid_s[:-1]) + 1
    edges = np.stack([idx_s[same - 1], idx_s[same]], axis=1)
    # chain position within each process run
    is_start = np.ones(P, bool)
    is_start[same] = False
    run_start = np.maximum.accumulate(np.where(is_start,
                                               np.arange(P), -1))
    pos_s = (np.arange(P) - run_start).astype(np.int32)
    pos = np.empty(P, np.int32)
    pos[order] = pos_s
    return idx, pid, pos, edges.astype(np.int32)


# -- append -------------------------------------------------------------------

@dataclass
class AppendBuild:
    """Everything append.check needs from the tensorized pass."""

    tensors: GraphTensors
    writer: dict                      # (k, v) -> writer history index
    orders: dict                      # k -> [values in version order]
    dup_anomalies: list
    order_anomalies: list
    micro_ops: int
    builder: str


def _encode_append(oks, infos):
    """Flat micro-op columns for append histories."""
    keys, kvs = Interner(), Interner()
    # append rows over oks then infos (writer-index order)
    a_txn, a_kv = [], []
    # read rows / read-element rows over oks only
    r_txn, r_key, r_len = [], [], []
    e_rid, e_pos, e_kv = [], [], []
    own_t, own_kv = [], []            # per-txn append set rows (oks)
    for group, is_ok in ((oks, True), (infos, False)):
        for op in group:
            for f, k, v in op.value or []:
                if f == APPEND:
                    a_txn.append(op.index)
                    a_kv.append(kvs.add((k, v)))
                    if is_ok:
                        own_t.append(op.index)
                        own_kv.append(a_kv[-1])
                elif is_ok and f == R and v is not None:
                    rid = len(r_txn)
                    r_txn.append(op.index)
                    r_key.append(keys.add(k))
                    r_len.append(len(v))
                    for p, x in enumerate(v):
                        e_rid.append(rid)
                        e_pos.append(p)
                        e_kv.append(kvs.add((k, x)))
    cols = {
        "a_txn": np.asarray(a_txn, np.int64),
        "a_kv": np.asarray(a_kv, np.int64),
        "r_txn": np.asarray(r_txn, np.int64),
        "r_key": np.asarray(r_key, np.int64),
        "r_len": np.asarray(r_len, np.int64),
        "e_rid": np.asarray(e_rid, np.int64),
        "e_pos": np.asarray(e_pos, np.int64),
        "e_kv": np.asarray(e_kv, np.int64),
        "own_t": np.asarray(own_t, np.int64),
        "own_kv": np.asarray(own_kv, np.int64),
    }
    return keys, kvs, cols


def _writer_from_rows(a_txn, a_kv, n_kv):
    """Last-assignment-wins writer array (kv id -> history index, -1
    none) plus per-kv distinct-writer count for dup detection."""
    writer = np.full(n_kv, -1, np.int64)
    if len(a_kv):
        # reversed unique keeps the LAST occurrence per kv
        _u, first = np.unique(a_kv[::-1], return_index=True)
        writer[_u] = a_txn[::-1][first]
        # dup check: same kv appended by more than one txn
        u_pairs = np.unique(np.stack([a_kv, a_txn], axis=1), axis=0)
        dup_mask = np.bincount(u_pairs[:, 0], minlength=n_kv) > 1
    else:
        dup_mask = np.zeros(n_kv, bool)
    return writer, dup_mask


def build_append(history: History, oks: list, infos: list,
                 additional_graphs=()) -> AppendBuild:
    """Tensorized equivalent of append._writer_index +
    append._version_orders + append.graph (+ additional graphs)."""
    t0 = _time.monotonic()
    keys, kvs, c = _encode_append(oks, infos)
    n_kv = len(kvs)
    builder = "tensor"

    writer_arr, dup_mask = _writer_from_rows(c["a_txn"], c["a_kv"], n_kv)
    from .append import _version_orders, _writer_index
    if dup_mask.any():
        # exact host payloads for the order-dependent anomaly lists
        writer, dups = _writer_index(oks, infos)
        builder = "host-fallback"
    else:
        writer = {_kv_key(kvs, i): int(writer_arr[i])
                  for i in range(n_kv) if writer_arr[i] >= 0}
        dups = []

    # version orders: clean iff every (key, position) sees one value
    orders_flat = None
    if len(c["e_rid"]):
        e_key = c["r_key"][c["e_rid"]]
        kp = e_key * (int(c["e_pos"].max()) + 2) + c["e_pos"]
        # clean iff one distinct kv per (key, position)
        u_kp = np.unique(kp)
        pair = np.unique(np.stack([kp, c["e_kv"]], axis=1), axis=0)
        per_kp = np.bincount(np.searchsorted(u_kp, pair[:, 0]),
                             minlength=len(u_kp))
        clean = bool((per_kp <= 1).all())
    else:
        clean = True
    if clean:
        orders, order_anoms = _orders_vectorized(keys, kvs, c)
    else:
        orders, order_anoms = _version_orders(oks)
        builder = "host-fallback"

    edges = _append_edges(keys, kvs, c, writer_arr, orders)

    parts = [edges]
    nodes = {int(op.index) for op in oks}
    if "realtime" in additional_graphs:
        ridx, rinv, rcomp, redges = realtime_arrays(history)
        if len(redges):
            parts.append(np.concatenate(
                [redges, np.full((len(redges), 1), REALTIME, np.int32)],
                axis=1))
        nodes |= {int(i) for i in np.unique(redges)} if len(redges) \
            else set()
    else:
        ridx = rinv = rcomp = None
    if "process" in additional_graphs:
        pidx, ppid, pp, pedges = process_arrays(history)
        if len(pedges):
            parts.append(np.concatenate(
                [pedges, np.full((len(pedges), 1), PROCESS, np.int32)],
                axis=1))
        nodes |= {int(i) for i in np.unique(pedges)} if len(pedges) \
            else set()
    else:
        pidx = ppid = pp = None

    all_edges = _dedup_edges(parts)
    node_arr = np.asarray(sorted(nodes | {int(x) for x in
                                          np.unique(all_edges[:, :2])}
                                 if len(all_edges) else nodes),
                          np.int32)
    inv_evt, comp_evt, proc, ppos = _jump_meta(
        node_arr, ridx, rinv, rcomp, pidx, ppid, pp)
    gt = GraphTensors(nodes=node_arr, edges=all_edges,
                      inv_evt=inv_evt, comp_evt=comp_evt,
                      proc=proc, proc_pos=ppos, analytic=True,
                      builder=builder,
                      build_s=_time.monotonic() - t0)
    return AppendBuild(tensors=gt, writer=writer, orders=orders,
                       dup_anomalies=dups, order_anomalies=order_anoms,
                       micro_ops=int(len(c["a_txn"]) + len(c["e_rid"])
                                     + len(c["r_txn"])),
                       builder=builder)


def _kv_key(kvs: Interner, i: int):
    k, v = kvs.objects[i]
    return (k, v)


def _orders_vectorized(keys, kvs, c):
    """Clean-path version orders: the longest read per key IS the
    order (all reads are prefixes of it — the clean check holds)."""
    orders: dict = {}
    if not len(c["r_txn"]):
        return orders, []
    # earliest read achieving the per-key max length
    r_key, r_len = c["r_key"], c["r_len"]
    order = np.lexsort((np.arange(len(r_key)), -r_len, r_key))
    k_sorted = r_key[order]
    firsts = np.flatnonzero(np.r_[True, k_sorted[1:] != k_sorted[:-1]])
    for f in firsts:
        rid = int(order[f])
        if c["r_len"][rid] == 0:
            continue
        mask = c["e_rid"] == rid
        kvi = c["e_kv"][mask][np.argsort(c["e_pos"][mask])]
        k = keys.objects[int(k_sorted[f])]
        orders[k] = [kvs.objects[int(i)][1] for i in kvi]
    return orders, []


def _append_edges(keys, kvs, c, writer_arr, orders):
    """ww/wr/rw edge rows from the columns + derived orders."""
    parts = []
    n_kv = len(kvs)
    # flatten orders into per-key kv arrays for ww + rw
    ord_kv, ord_key_off, key_list = [], {}, []
    for k, vals in orders.items():
        ids = [kvs.get((k, v)) for v in vals]
        ord_key_off[keys.add(k)] = (len(ord_kv), len(vals))
        ord_kv.extend(-1 if i is None else i for i in ids)
    ord_kv = np.asarray(ord_kv, np.int64)

    # ww: consecutive order entries with live writers
    if len(ord_kv) > 1:
        offs = np.asarray([[o, n] for o, n in ord_key_off.values()],
                          np.int64)
        pos = []
        for o, n in offs:
            pos.extend(range(o, o + n - 1))
        pos = np.asarray(pos, np.int64)
        if len(pos):
            kv1, kv2 = ord_kv[pos], ord_kv[pos + 1]
            ok = (kv1 >= 0) & (kv2 >= 0)
            w1 = np.where(ok, writer_arr[np.maximum(kv1, 0)], -1)
            w2 = np.where(ok, writer_arr[np.maximum(kv2, 0)], -1)
            m = (w1 >= 0) & (w2 >= 0)
            if m.any():
                parts.append(np.stack(
                    [w1[m], w2[m], np.full(int(m.sum()), WW)],
                    axis=1).astype(np.int32))

    # wr: last non-own element of each read -> reader
    if len(c["e_rid"]):
        stride = n_kv + 1
        own_set = np.unique(c["own_t"] * stride + c["own_kv"]) \
            if len(c["own_t"]) else np.zeros(0, np.int64)
        e_txn = c["r_txn"][c["e_rid"]]
        e_own = np.isin(e_txn * stride + c["e_kv"], own_set)
        pos_m = np.where(e_own, np.int64(-1), c["e_pos"])
        order = np.lexsort((pos_m, c["e_rid"]))
        rid_s, pos_s, kv_s = (c["e_rid"][order], pos_m[order],
                              c["e_kv"][order])
        last = np.flatnonzero(np.r_[rid_s[1:] != rid_s[:-1], True])
        keep = pos_s[last] >= 0
        rid_l, kv_l = rid_s[last][keep], kv_s[last][keep]
        w = writer_arr[kv_l]
        m = w >= 0
        if m.any():
            parts.append(np.stack(
                [w[m], c["r_txn"][rid_l[m]],
                 np.full(int(m.sum()), WR)], axis=1).astype(np.int32))

    # rw: read of a strict prefix -> writer of the next version
    if len(c["r_txn"]):
        nxt = np.full(len(c["r_txn"]), -1, np.int64)
        for rid in range(len(c["r_txn"])):
            off_n = ord_key_off.get(int(c["r_key"][rid]))
            if off_n is None:
                continue
            o, n = off_n
            plen = int(c["r_len"][rid])
            if plen < n:
                nxt[rid] = ord_kv[o + plen]
        ok = nxt >= 0
        w = np.where(ok, writer_arr[np.maximum(nxt, 0)], -1)
        m = w >= 0
        if m.any():
            parts.append(np.stack(
                [c["r_txn"][m], w[m],
                 np.full(int(m.sum()), RW)], axis=1).astype(np.int32))
    return _dedup_edges(parts)


def _jump_meta(node_arr, ridx, rinv, rcomp, pidx, ppid, pp):
    """Align realtime/process metadata with the node array (local
    ids). Absent entries get sentinels that disable the jump."""
    T = len(node_arr)
    inv_evt = np.full(T, -_BIG, np.int64)
    comp_evt = np.full(T, _BIG, np.int64)
    proc = np.full(T, -1, np.int32)
    ppos = np.full(T, -1, np.int32)
    if ridx is not None and len(ridx):
        loc = np.searchsorted(node_arr, ridx)
        m = (loc < T) & (node_arr[np.minimum(loc, T - 1)] == ridx)
        inv_evt[loc[m]] = rinv[m]
        comp_evt[loc[m]] = rcomp[m]
    if pidx is not None and len(pidx):
        loc = np.searchsorted(node_arr, pidx)
        m = (loc < T) & (node_arr[np.minimum(loc, T - 1)] == pidx)
        proc[loc[m]] = ppid[m]
        ppos[loc[m]] = pp[m]
    return inv_evt, comp_evt, proc, ppos


# -- wr -----------------------------------------------------------------------

@dataclass
class WrBuild:
    tensors: GraphTensors
    writer: dict
    orders: dict                      # k -> {v: set(successors)}
    cyclic_anomalies: list
    micro_ops: int
    builder: str


def build_wr(history: History, oks: list, infos: list,
             sequential_keys=False, linearizable_keys=False,
             wfr_keys=False, additional_graphs=()) -> WrBuild:
    """Tensorized equivalent of wr._writer_index + wr._version_orders
    + wr._txn_graph (+ additional graphs). Evidence-pair derivation is
    vectorized per source; the per-key cycle check stays host-side
    (pair counts are tiny) and cyclic keys keep host-exact payloads."""
    t0 = _time.monotonic()
    from .wr import INIT, _fmt_pairs, _has_cycle

    keys, kvs = Interner(), Interner()
    # mop rows over oks, in op order
    m_txn, m_seq, m_mop, m_key, m_kv, m_isw, m_proc = \
        [], [], [], [], [], [], []
    w_rows_txn, w_rows_kv = [], []    # writes over oks + infos
    for seq, op in enumerate(oks):
        for mi, (f, k, v) in enumerate(op.value):
            if f not in (R, W):
                continue
            kid = keys.add(k)
            cur = kvs.add((k, INIT)) if (f == R and v is None) \
                else kvs.add((k, v))
            m_txn.append(op.index)
            m_seq.append(seq)
            m_mop.append(mi)
            m_key.append(kid)
            m_kv.append(cur)
            m_isw.append(f == W)
            m_proc.append(op.process)
            if f == W:
                w_rows_txn.append(op.index)
                w_rows_kv.append(cur)
    for op in infos:
        for f, k, v in op.value or []:
            if f == W:
                w_rows_txn.append(op.index)
                w_rows_kv.append(kvs.add((k, v)))
    n_kv = len(kvs)
    init_ids = np.asarray([kvs.add((keys.objects[i], INIT))
                           for i in range(len(keys))], np.int64) \
        if len(keys) else np.zeros(0, np.int64)
    n_kv = len(kvs)

    writer_arr = np.full(n_kv, -1, np.int64)
    if w_rows_kv:
        wkv = np.asarray(w_rows_kv, np.int64)
        wtx = np.asarray(w_rows_txn, np.int64)
        u, first = np.unique(wkv[::-1], return_index=True)
        writer_arr[u] = wtx[::-1][first]
    writer = {tuple(kvs.objects[i]): int(writer_arr[i])
              for i in range(n_kv) if writer_arr[i] >= 0}

    M = len(m_txn)
    mt = np.asarray(m_txn, np.int64)
    ms = np.asarray(m_seq, np.int64)
    mm = np.asarray(m_mop, np.int64)
    mk = np.asarray(m_key, np.int64)
    mkv = np.asarray(m_kv, np.int64)
    miw = np.asarray(m_isw, bool)

    pair_parts = []   # (key, v1_kv, v2_kv) evidence rows

    if M:
        # INIT precedes every written value
        wm = miw
        if wm.any():
            pair_parts.append(np.stack(
                [mk[wm], init_ids[mk[wm]], mkv[wm]], axis=1))
        # wfr: last read of k in the txn before a write of k
        if wfr_keys and wm.any():
            order = np.lexsort((mm, mk, ms))
            seq_s, key_s, mop_s = ms[order], mk[order], mm[order]
            kv_s, isw_s = mkv[order], miw[order]
            grp = np.r_[True, (seq_s[1:] != seq_s[:-1])
                        | (key_s[1:] != key_s[:-1])]
            # forward-fill index of last READ row within each group
            ridx = np.where(~isw_s, np.arange(len(order)), -1)
            ridx[grp & (ridx < 0)] = -1
            # reset at group starts: offset trick
            gid = np.cumsum(grp) - 1
            filled = np.maximum.accumulate(
                np.where(~isw_s, np.arange(len(order)) + gid * 0, -1)
                + gid * len(order))
            filled = filled - gid * len(order)
            valid = filled >= 0
            tgt = np.flatnonzero(isw_s & valid)
            if len(tgt):
                lr_kv = kv_s[filled[tgt]]
                pairs = np.stack([key_s[tgt], lr_kv, kv_s[tgt]],
                                 axis=1)
                pairs = pairs[pairs[:, 1] != pairs[:, 2]]
                if len(pairs):
                    pair_parts.append(pairs)
        # sequential: consecutive distinct observations per (proc, key)
        if sequential_keys:
            procs = Interner()
            mp = np.asarray([procs.add(p) for p in m_proc], np.int64)
            order = np.lexsort((mm, ms, mk, mp))
            p_s, k_s, kv_s = mp[order], mk[order], mkv[order]
            adj = np.flatnonzero((p_s[1:] == p_s[:-1])
                                 & (k_s[1:] == k_s[:-1])
                                 & (kv_s[1:] != kv_s[:-1])) + 1
            if len(adj):
                pair_parts.append(np.stack(
                    [k_s[adj], kv_s[adj - 1], kv_s[adj]], axis=1))
        if linearizable_keys:
            ev = _wr_realtime_evidence(history, keys, kvs, INIT)
            if ev is not None and len(ev):
                pair_parts.append(ev)

    pairs = (np.unique(np.concatenate(pair_parts, axis=0), axis=0)
             if pair_parts else np.zeros((0, 3), np.int64))

    # per-key cycle check + adjacency dict (host, tiny)
    orders: dict = {}
    cyclic: list = []
    if len(pairs):
        for kid in np.unique(pairs[:, 0]):
            rows = pairs[pairs[:, 0] == kid]
            adj: dict = {}
            for _k, a, b in rows:
                adj.setdefault(int(a), set()).add(int(b))
            k = keys.objects[int(kid)]
            obj = {(_obj(kvs, a, INIT)): {_obj(kvs, b, INIT)
                                          for b in bs}
                   for a, bs in adj.items()}
            if _has_cycle({a: set(bs) for a, bs in adj.items()}):
                raw = {( _obj(kvs, int(a), INIT), _obj(kvs, int(b), INIT))
                       for _kk, a, b in rows}
                cyclic.append({"key": k,
                               "explanation":
                               f"version precedence evidence for key "
                               f"{k!r} is cyclic: {_fmt_pairs(raw)}"})
            else:
                orders[k] = obj

    edges = _wr_edges(keys, kvs, oks, writer_arr, pairs, cyclic,
                      init_ids, INIT)
    parts = [edges]
    nodes = {int(op.index) for op in oks}
    ridx = rinv = rcomp = None
    pidx = ppid = pp = None
    if "realtime" in additional_graphs:
        ridx, rinv, rcomp, redges = realtime_arrays(history)
        if len(redges):
            parts.append(np.concatenate(
                [redges, np.full((len(redges), 1), REALTIME, np.int32)],
                axis=1))
            nodes |= {int(i) for i in np.unique(redges)}
    if "process" in additional_graphs:
        pidx, ppid, pp, pedges = process_arrays(history)
        if len(pedges):
            parts.append(np.concatenate(
                [pedges, np.full((len(pedges), 1), PROCESS, np.int32)],
                axis=1))
            nodes |= {int(i) for i in np.unique(pedges)}
    all_edges = _dedup_edges(parts)
    node_arr = np.asarray(sorted(nodes | ({int(x) for x in
                                           np.unique(all_edges[:, :2])}
                                          if len(all_edges) else set())),
                          np.int32)
    inv_evt, comp_evt, proc, ppos = _jump_meta(
        node_arr, ridx, rinv, rcomp, pidx, ppid, pp)
    gt = GraphTensors(nodes=node_arr, edges=all_edges,
                      inv_evt=inv_evt, comp_evt=comp_evt,
                      proc=proc, proc_pos=ppos, analytic=True,
                      builder="tensor",
                      build_s=_time.monotonic() - t0)
    return WrBuild(tensors=gt, writer=writer, orders=orders,
                   cyclic_anomalies=cyclic, micro_ops=M,
                   builder="tensor")


def _obj(kvs: Interner, kv_id: int, INIT):
    v = kvs.objects[int(kv_id)][1]
    return v


def _wr_realtime_evidence(history, keys, kvs, INIT):
    """wr._realtime_evidence as columns: per key, the running
    latest-completed final value (strictly-max completion time, first
    writer kept on ties) versus each op's first observation.

    The encode is element-row flat (the treatment append's encoder
    got): ONE append per R/W micro-op — no per-op first/final dicts,
    no Python sweep — then numpy does the rest: the sweep rank is a
    stable argsort of invocation times, and each op's first/final
    observation per key falls out of one lexsort over (pair, key,
    mop-position) as the group's first/last element. Past ~1M
    micro-ops the old per-op dict loop was the build's floor; this
    keeps the wr evidence derivation on the vectorized path the rest
    of the builder already runs."""
    pairs = [(inv, comp) for inv, comp in history.pairs()
             if comp is not None and comp.is_ok and comp.value]
    if not pairs:
        return None
    if not _times_ok([p[0] for p in pairs] + [p[1] for p in pairs]):
        raise BuildUnsupported("non-integer op times")
    # flat element-row encode: the interner adds are the only Python
    # left (ids must come from the build's shared Interner instances)
    e_p, e_kid, e_kv, e_pos = [], [], [], []
    inv_t = np.empty(len(pairs), np.int64)
    comp_t = np.empty(len(pairs), np.int64)
    kadd, vadd = keys.add, kvs.add
    for p, (inv, comp) in enumerate(pairs):
        inv_t[p] = inv.time
        comp_t[p] = comp.time
        for pos, (f, k, v) in enumerate(comp.value):
            if f == R:
                cur = vadd((k, INIT)) if v is None else vadd((k, v))
            elif f == W:
                cur = vadd((k, v))
            else:
                continue
            e_p.append(p)
            e_kid.append(kadd(k))
            e_kv.append(cur)
            e_pos.append(pos)
    if not e_p:
        return None
    ep = np.asarray(e_p, np.int64)
    ekid = np.asarray(e_kid, np.int64)
    ekv = np.asarray(e_kv, np.int64)
    epos = np.asarray(e_pos, np.int64)
    # sweep rank = stable sort by invocation time (host sweep order)
    order = np.argsort(inv_t, kind="stable")
    sweep = np.empty(len(pairs), np.int64)
    sweep[order] = np.arange(len(pairs))
    # first/final observation per (op, key): group rows by
    # (pair, key) in mop order; the group's first element is `first`,
    # its last is `final` — exactly the old dicts, without them
    o2 = np.lexsort((epos, ekid, ep))
    p_s, kid_s, kv_s = ep[o2], ekid[o2], ekv[o2]
    newgrp = np.r_[True, (p_s[1:] != p_s[:-1])
                   | (kid_s[1:] != kid_s[:-1])]
    last_idx = np.r_[np.flatnonzero(newgrp)[1:] - 1, len(p_s) - 1]
    grp_p = p_s[newgrp]
    rk = kid_s[newgrp]
    ri = sweep[grp_p]
    rf = kv_s[newgrp]
    rl = kv_s[last_idx]
    rt_inv = inv_t[grp_p]
    rt_comp = comp_t[grp_p]
    n = len(rk)
    order2 = np.lexsort((ri, rk))
    k_s = rk[order2]
    # rank-compress times so the composite below cannot overflow
    # int64 even with nanosecond stamps: ranks preserve both < and ==
    # across comp and inv because they come from ONE unique array
    uniq_t = np.unique(np.concatenate([rt_comp, rt_inv]))
    comp_r = np.searchsorted(uniq_t, rt_comp[order2]).astype(np.int64)
    inv_r = np.searchsorted(uniq_t, rt_inv[order2]).astype(np.int64)
    # composite running max: strictly larger comp_time wins, first
    # achiever kept on ties (host `latest[k][0] < comp.time`)
    KBASE = np.int64(n + 1)
    comp_scaled = comp_r * KBASE + (KBASE - 1 - np.arange(n))
    seg = np.cumsum(np.r_[True, k_s[1:] != k_s[:-1]]) - 1
    span = np.int64(int(comp_scaled.max()) + 1) if n else np.int64(1)
    glob = comp_scaled + seg * (2 * span)
    run = np.maximum.accumulate(glob)
    # value BEFORE this row (shift within segment)
    prev_run = np.r_[np.int64(-1), run[:-1]]
    seg_start = np.r_[True, k_s[1:] != k_s[:-1]]
    have_prev = ~seg_start
    prev_comp_scaled = prev_run - seg * (2 * span)
    prev_t = np.where(have_prev, prev_comp_scaled // KBASE, -1)
    prev_row = np.where(have_prev,
                        KBASE - 1 - (prev_comp_scaled % KBASE), -1)
    first_s = rf[order2]
    inv_s = inv_r
    prev_val = np.where(prev_row >= 0, rl[order2][
        np.maximum(prev_row, 0)], -1)
    m = have_prev & (prev_t < inv_s) & (prev_val != first_s) \
        & (prev_val >= 0)
    if not m.any():
        return np.zeros((0, 3), np.int64)
    return np.stack([k_s[m], prev_val[m], first_s[m]], axis=1)


def _wr_edges(keys, kvs, oks, writer_arr, pairs, cyclic, init_ids,
              INIT):
    """ww/wr/rw rows from the wr evidence pairs (cyclic keys carry no
    order, so they contribute no ww/rw edges — host parity)."""
    parts = []
    cyc_kids = {keys.get(c["key"]) for c in cyclic}
    if len(pairs):
        ok_rows = np.asarray([int(r[0]) not in cyc_kids for r in pairs],
                             bool)
        live = pairs[ok_rows]
        if len(live):
            w1 = writer_arr[live[:, 1]]
            w2 = writer_arr[live[:, 2]]
            m = (w1 >= 0) & (w2 >= 0)
            if m.any():
                parts.append(np.stack(
                    [w1[m], w2[m], np.full(int(m.sum()), WW)],
                    axis=1).astype(np.int32))
    # ext reads: first mop of a key in a txn that is a read
    from ..txn import ext_reads
    er_txn, er_kv, er_real = [], [], []
    for op in oks:
        for k, v in ext_reads(op.value).items():
            if keys.get(k) is None:
                continue
            cur = kvs.get((k, INIT)) if v is None else kvs.get((k, v))
            er_txn.append(op.index)
            er_kv.append(-1 if cur is None else cur)
            er_real.append(v is not None and cur is not None)
    if er_txn:
        ekv = np.asarray(er_kv, np.int64)
        etx = np.asarray(er_txn, np.int64)
        m = np.asarray(er_real, bool) & (ekv >= 0)
        m[m] &= writer_arr[ekv[m]] >= 0
        if m.any():
            parts.append(np.stack(
                [writer_arr[ekv[m]], etx[m],
                 np.full(int(m.sum()), WR)], axis=1).astype(np.int32))
    # rw: evidenced successors of the observed version
    if len(pairs) and er_txn:
        live = pairs[np.asarray([int(r[0]) not in cyc_kids
                                 for r in pairs], bool)]
        if len(live):
            ek = np.asarray(er_kv, np.int64)
            et = np.asarray(er_txn, np.int64)
            ok = ek >= 0
            # join ext-read kv against evidence v1
            order = np.argsort(live[:, 1], kind="stable")
            v1_s = live[order, 1]
            lo = np.searchsorted(v1_s, ek[ok], side="left")
            hi = np.searchsorted(v1_s, ek[ok], side="right")
            counts = hi - lo
            total = int(counts.sum())
            if total:
                src_rep = np.repeat(et[ok], counts)
                offs = np.arange(total) - np.repeat(
                    np.concatenate([[0], np.cumsum(counts)[:-1]]),
                    counts)
                rows = order[np.repeat(lo, counts) + offs]
                nxt = live[rows, 2]
                w = writer_arr[nxt]
                m = w >= 0
                if m.any():
                    parts.append(np.stack(
                        [src_rep[m], w[m],
                         np.full(int(m.sum()), RW)],
                        axis=1).astype(np.int32))
    return _dedup_edges(parts)
