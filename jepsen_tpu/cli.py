"""Command-line interface framework.

Capability parity with jepsen.cli (`jepsen/src/jepsen/cli.clj`): a
declarative option-spec language that per-suite runners can extend and
merge (cli.clj:52-59), the standard test option set (cli.clj:64-111),
node-list merging from `-n`/`--nodes`/`--nodes-file` (cli.clj:170-205),
`"3n"` concurrency sugar (cli.clj:150-168), and the subcommand
dispatcher with the reference's exit-code contract (cli.clj:129-139):

  0    all tests passed
  1    some test failed
  2    some test had an unknown validity
  254  invalid arguments / unknown command
  255  internal framework error

Commands are plain dicts `{"name": {"opt_spec", "opt_fn", "usage",
"run"}}` so suites compose them with `dict`-merge, exactly as the
reference composes `single-test-cmd`/`test-all-cmd`/`serve-cmd` maps
(cli.clj:355,491,336). `run` returns an exit code (or None for 0);
`run_cli` returns the code rather than exiting so it is testable —
`main()` wraps it in `sys.exit`.

The option parser is deliberately tiny and declarative rather than
argparse-based: the reference semantics (repeated options replacing a
shared default list, spec merging by option name, validation messages
collected rather than thrown) map poorly onto argparse's global
mutable parser objects.
"""

from __future__ import annotations

import logging
import os
import re
import sys
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

log = logging.getLogger("jepsen_tpu.cli")

DEFAULT_NODES = ["n1", "n2", "n3", "n4", "n5"]

EXIT_OK = 0
EXIT_INVALID = 1
EXIT_UNKNOWN = 2
EXIT_BAD_ARGS = 254
EXIT_ERROR = 255


def one_of(coll) -> str:
    """Validation help string listing legal values (cli.clj:20-25)."""
    names = sorted(coll.keys() if isinstance(coll, dict) else coll)
    return "Must be one of " + ", ".join(str(n) for n in names)


@dataclass
class Opt:
    """One command-line option.

    name      key in the parsed options map (underscored)
    long      long flag ("--node"); derived from name if None
    short     optional short flag ("-n")
    metavar   argument placeholder; a flag takes no argument if None
    help      docstring
    default   initial value
    parse     str -> value
    validate  (predicate, message)
    repeated  collect into a list, replacing the default wholesale on
              the first occurrence (cli.clj:27-50)
    """

    name: str
    help: str = ""
    short: Optional[str] = None
    long: Optional[str] = None
    metavar: Optional[str] = None
    default: Any = None
    parse: Optional[Callable[[str], Any]] = None
    validate: Optional[tuple] = None
    repeated: bool = False

    def __post_init__(self):
        if self.long is None:
            self.long = "--" + self.name.replace("_", "-")

    @property
    def takes_arg(self) -> bool:
        return self.metavar is not None

    def summary_line(self) -> str:
        flags = ", ".join(f for f in (self.short, self.long) if f)
        if self.takes_arg:
            flags += " " + self.metavar
        dflt = f" (default: {self.default})" if self.default not in (
            None, False) else ""
        return f"  {flags:<34} {self.help}{dflt}"


def pos_int(s: str) -> int:
    v = int(s)
    if v <= 0:
        raise ValueError(f"{v} must be positive")
    return v


def comma_list(s: str) -> list:
    return [p for p in re.split(r",\s*", s) if p]


TEST_OPT_SPEC: list = [
    Opt("help", short="-h", help="Print out this message and exit"),
    Opt("node", short="-n", metavar="HOSTNAME", repeated=True,
        default=DEFAULT_NODES,
        help="Node(s) to run the test on; may be given many times."),
    Opt("nodes", metavar="NODE_LIST", parse=comma_list,
        help="Comma-separated list of node hostnames."),
    Opt("nodes_file", metavar="FILENAME",
        help="File containing node hostnames, one per line."),
    Opt("username", metavar="USER", default="root",
        help="Username for logins"),
    Opt("password", metavar="PASS", default="root",
        help="Password for sudo access"),
    Opt("strict_host_key_checking", default=False,
        help="Whether to check host keys"),
    Opt("no_ssh", default=False,
        help="Don't establish SSH connections to any nodes."),
    Opt("ssh_private_key", metavar="FILE",
        help="Path to an SSH identity file"),
    Opt("concurrency", metavar="NUMBER", default="1n",
        validate=(lambda s: re.fullmatch(r"\d+n?", str(s)),
                  "Must be an integer, optionally followed by n."),
        help="How many workers to run; an integer, optionally followed "
             "by n (e.g. 3n) to multiply by the number of nodes."),
    Opt("leave_db_running", default=False,
        help="Leave the database running at the end of the test."),
    Opt("logging_json", default=False,
        help="Use JSON structured output in the log."),
    Opt("test_count", metavar="NUMBER", default=1, parse=pos_int,
        help="How many times to repeat the test"),
    Opt("time_limit", metavar="SECONDS", default=60, parse=pos_int,
        help="Excluding setup and teardown, how long to run the test"),
]


def merge_opt_specs(a: Sequence[Opt], b: Sequence[Opt]) -> list:
    """Merge two option specs; where both define the same option name
    the latter wins (cli.clj:52-59)."""
    out: list = []
    names: dict = {}
    for o in list(a) + list(b):
        if o.name in names:
            out[names[o.name]] = o
        else:
            names[o.name] = len(out)
            out.append(o)
    return out


@dataclass
class Parsed:
    """Result of option parsing: the opts map, positional arguments,
    accumulated error strings, and a help summary."""

    options: dict = field(default_factory=dict)
    arguments: list = field(default_factory=list)
    errors: list = field(default_factory=list)
    summary: str = ""


def parse_opts(argv: Sequence[str], spec: Sequence[Opt]) -> Parsed:
    """Parse argv against an option spec. Collects (rather than
    raises) errors so the caller can print them and exit 254."""
    by_flag: dict = {}
    for o in spec:
        by_flag[o.long] = o
        if o.short:
            by_flag[o.short] = o

    p = Parsed(options={o.name: o.default for o in spec},
               summary="\n".join(o.summary_line() for o in spec))
    replaced: set = set()  # repeated opts that dropped their default
    args = list(argv)
    i = 0
    while i < len(args):
        tok = args[i]
        i += 1
        if not tok.startswith("-") or tok == "-":
            p.arguments.append(tok)
            continue
        if tok == "--":
            p.arguments.extend(args[i:])
            break
        flag, _, inline = tok.partition("=")
        o = by_flag.get(flag)
        if o is None:
            p.errors.append(f"Unknown option: {flag}")
            continue
        if not o.takes_arg:
            val: Any = True
        elif inline or _:
            val = inline
        elif i < len(args):
            val = args[i]
            i += 1
        else:
            p.errors.append(f"Missing required argument for {flag}")
            continue
        if o.takes_arg:
            if o.validate and not o.validate[0](val):
                p.errors.append(
                    f'Failed to validate "{flag} {val}": {o.validate[1]}')
                continue
            if o.parse:
                try:
                    val = o.parse(val)
                except Exception as e:  # noqa: BLE001
                    p.errors.append(f'Error parsing "{flag} {val}": {e}')
                    continue
        if o.repeated:
            if o.name in replaced:
                p.options[o.name].append(val)
            else:
                replaced.add(o.name)
                p.options[o.name] = [val]
        else:
            p.options[o.name] = val
    return p


# -- Option post-processing (test-opt-fn, cli.clj:245-254) -----------------

def parse_concurrency(parsed: Parsed, key: str = "concurrency") -> Parsed:
    """Resolve "3n"-style concurrency to an integer (cli.clj:150-168)."""
    c = str(parsed.options.get(key))
    m = re.fullmatch(r"(\d+)(n?)", c)
    if not m:
        raise ValueError(
            f"--{key} {c} should be an integer optionally followed by n")
    unit = len(parsed.options.get("nodes") or []) if m.group(2) else 1
    parsed.options[key] = int(m.group(1)) * unit
    return parsed


def parse_nodes(parsed: Parsed) -> Parsed:
    """Merge `-n`, `--nodes`, and `--nodes-file` into a single "nodes"
    list (cli.clj:170-205). Explicit sources drop the default list."""
    o = parsed.options
    node = o.get("node")
    nodes = o.get("nodes")
    nodes_file = o.get("nodes_file")
    if node is DEFAULT_NODES and (nodes or nodes_file):
        node = None
    file_nodes = None
    if nodes_file:
        with open(nodes_file) as f:
            file_nodes = [ln.strip() for ln in f if ln.strip()]
    all_nodes = list(file_nodes or []) + list(nodes or []) + list(node or [])
    o.pop("node", None)
    o.pop("nodes_file", None)
    o["nodes"] = all_nodes
    return parsed


def rename_ssh_options(parsed: Parsed) -> Parsed:
    """Bundle the SSH flags into an "ssh" map (cli.clj:224-243)."""
    o = parsed.options
    o["ssh"] = {
        "dummy?": bool(o.pop("no_ssh", False)),
        "username": o.pop("username", None),
        "password": o.pop("password", None),
        "strict_host_key_checking": o.pop("strict_host_key_checking",
                                          False),
        "private_key_path": o.pop("ssh_private_key", None),
    }
    return parsed


def rename_options(parsed: Parsed, renames: dict) -> Parsed:
    for old, new in renames.items():
        if old in parsed.options:
            parsed.options[new] = parsed.options.pop(old)
    return parsed


def test_opt_fn(parsed: Parsed) -> Parsed:
    """The standard post-processing chain for test commands
    (cli.clj:245-254)."""
    parsed = rename_ssh_options(parsed)
    parsed = rename_options(parsed, {"leave_db_running":
                                     "leave_db_running?",
                                     "logging_json": "logging_json?"})
    parsed = parse_nodes(parsed)
    parsed = parse_concurrency(parsed)
    return parsed


# -- Subcommand dispatcher (cli.clj:258-332) -------------------------------

def run_cli(subcommands: dict, argv: Sequence[str],
            prog: str = "jepsen_tpu") -> int:
    """Dispatch argv[0] to a subcommand map and return an exit code.

    Each subcommand is `{"opt_spec": [...], "opt_fn": fn, "usage": str,
    "run": fn(Parsed) -> int|None}`.
    """
    assert "--help" not in subcommands and "help" not in subcommands
    try:
        command = argv[0] if argv else None
        if command not in subcommands:
            print(f"Usage: python -m {prog} COMMAND [OPTIONS ...]")
            print("Commands:", ", ".join(sorted(subcommands)))
            return EXIT_BAD_ARGS

        sub = subcommands[command]
        opt_fn = sub.get("opt_fn") or (lambda p: p)
        usage = sub.get("usage") or (
            f"Usage: python -m {prog} {command} [OPTIONS ...]")
        run = sub.get("run")

        parsed = parse_opts(argv[1:], sub.get("opt_spec") or [])
        summary = parsed.summary
        if parsed.options.get("help"):
            print(usage)
            print()
            print(summary)
            return EXIT_OK
        if not parsed.errors:
            try:
                parsed = opt_fn(parsed)
            except Exception as e:  # noqa: BLE001
                parsed.errors.append(str(e))
        if parsed.errors:
            for e in parsed.errors:
                print(e, file=sys.stderr)
            return EXIT_BAD_ARGS
        parsed.options["argv"] = list(argv)

        if run is None:
            print("Options:")
            for k in sorted(parsed.options):
                print(f"  {k}: {parsed.options[k]!r}")
            return EXIT_OK
        rc = run(parsed)
        return EXIT_OK if rc is None else int(rc)
    except SystemExit as e:
        return int(e.code or 0)
    except BrokenPipeError:
        return EXIT_OK  # stdout closed (e.g. piped through head)
    except BaseException:  # noqa: BLE001
        print("Oh jeez, I'm sorry, jepsen_tpu broke. Here's why:",
              file=sys.stderr)
        traceback.print_exc()
        return EXIT_ERROR


TEST_USAGE = """Usage: python -m jepsen_tpu COMMAND [OPTIONS ...]

Runs a test and exits with a status code:

  0     All tests passed
  1     Some test failed
  2     Some test had an unknown validity
  254   Invalid arguments
  255   Internal error

Options:"""


def _validity_code(test: dict) -> int:
    v = (test.get("results") or {}).get("valid?")
    if v is False:
        return EXIT_INVALID
    if v == "unknown":
        return EXIT_UNKNOWN
    return EXIT_OK


def single_test_cmd(opts: dict) -> dict:
    """Build `test` and `analyze` commands around a test_fn
    (cli.clj:355-431).

    opts: {"test_fn": options-map -> test-map,
           "opt_spec": extra Opts (merged into TEST_OPT_SPEC),
           "opt_fn": extra post-processing composed after test_opt_fn,
           "usage": usage string}
    """
    opt_spec = merge_opt_specs(TEST_OPT_SPEC, opts.get("opt_spec") or [])
    extra = opts.get("opt_fn")
    opt_fn = (lambda p: extra(test_opt_fn(p))) if extra else test_opt_fn
    test_fn = opts["test_fn"]
    usage = opts.get("usage", TEST_USAGE)

    def run_test(parsed: Parsed):
        from . import core
        options = parsed.options
        log.info("Test options: %r", options)
        for _ in range(options.get("test_count") or 1):
            test = core.run(test_fn(options))
            rc = _validity_code(test)
            if rc != EXIT_OK:
                return rc
        return EXIT_OK

    def run_analyze(parsed: Parsed):
        """Re-analyze the latest stored history with a freshly built
        test map (cli.clj:402-431)."""
        from . import core, store
        options = parsed.options
        cli_test = test_fn(options)
        root = options.get("store_root") or store.BASE_DIR
        latest = store.latest(root)
        if latest is None:
            raise RuntimeError("Not sure what the last test was")
        stored = store.load_latest(root)
        if stored.get("name") != cli_test.get("name"):
            raise RuntimeError(
                f"Stored test ({stored.get('name')}) and CLI test "
                f"({cli_test.get('name')}) have different names; aborting")
        stored.pop("results", None)
        test = {**cli_test, **stored}
        test = core.analyze(test)
        writer = store.Writer(test)
        try:
            test["store_dir"] = writer.dir
            writer.save_0(test)
            writer.save_1(test)
            writer.save_2(test)
        finally:
            writer.close()
        core.log_results(test)
        return _validity_code(test)

    return {
        "test": {"opt_spec": opt_spec, "opt_fn": opt_fn, "usage": usage,
                 "run": run_test},
        "analyze": {"opt_spec": opt_spec, "opt_fn": opt_fn, "usage": usage,
                    "run": run_analyze},
    }


def test_all_run_tests(tests) -> dict:
    """Run a sequence of tests; map outcome (True / "unknown" / False /
    "crashed") -> list of store paths (cli.clj:433-451)."""
    from . import core, store
    outcomes: dict = {}
    for test in tests:
        test = core.prepare_test(test)
        where = None
        try:
            done = core.run(test)
            where = done.get("store_dir") or store.path(done)
            outcome = (done.get("results") or {}).get("valid?")
        except Exception:  # noqa: BLE001
            log.warning("Test crashed", exc_info=True)
            where = test.get("store_dir") or test.get("name")
            outcome = "crashed"
        outcomes.setdefault(outcome, []).append(where)
    return outcomes


def test_all_print_summary(results: dict) -> dict:
    """Human summary of a test-all run (cli.clj:453-481)."""
    for outcome, title in ((True, "Successful tests"),
                           ("unknown", "Indeterminate tests"),
                           ("crashed", "Crashed tests"),
                           (False, "Failed tests")):
        if results.get(outcome):
            print(f"\n# {title}\n")
            for p in results[outcome]:
                print(p)
    print()
    print(len(results.get(True, [])), "successes")
    print(len(results.get("unknown", [])), "unknown")
    print(len(results.get("crashed", [])), "crashed")
    print(len(results.get(False, [])), "failures")
    return results


def test_all_exit_code(results: dict) -> int:
    """255 if any crashed, 2 if any unknown, 1 if any invalid, else 0
    (cli.clj:483-491)."""
    if results.get("crashed"):
        return EXIT_ERROR
    if results.get("unknown"):
        return EXIT_UNKNOWN
    if results.get(False):
        return EXIT_INVALID
    return EXIT_OK


def test_all_cmd(opts: dict) -> dict:
    """Build a `test-all` command around a tests_fn: options-map -> seq
    of test maps (cli.clj:493-519)."""
    opt_spec = merge_opt_specs(TEST_OPT_SPEC, opts.get("opt_spec") or [])
    extra = opts.get("opt_fn")
    opt_fn = (lambda p: extra(test_opt_fn(p))) if extra else test_opt_fn
    tests_fn = opts["tests_fn"]

    def run(parsed: Parsed):
        log.info("CLI options: %r", parsed.options)
        results = test_all_run_tests(tests_fn(parsed.options))
        test_all_print_summary(results)
        return test_all_exit_code(results)

    return {"test-all": {"opt_spec": opt_spec, "opt_fn": opt_fn,
                         "usage": "Runs all tests", "run": run}}


def serve_cmd() -> dict:
    """Build the results web-server command (cli.clj:334-354). With
    --service it also fronts the checker-as-a-service admission queue
    (jepsen_tpu/service.py): POST /check, SSE at /events and
    /runs/<id>/events, objectives at /slo."""
    spec = [
        Opt("help", short="-h", help="Print out this message and exit"),
        Opt("host", short="-b", metavar="HOST", default="0.0.0.0",
            help="Hostname to bind to"),
        Opt("port", short="-p", metavar="NUMBER", default=8080,
            parse=pos_int, help="Port number to bind to"),
        Opt("store_root", metavar="DIR", default="store",
            help="Store directory to serve"),
        Opt("service", default=False,
            help="Attach the checker service (POST /check + SSE + "
                 "warm worker pool; re-warms cached bucket plans)"),
        Opt("workers", metavar="N", default=1, parse=pos_int,
            help="Service worker threads (with --service)"),
        Opt("quota_device_s", metavar="SECONDS", parse=float,
            help="Per-tenant device-seconds quota over the rolling "
                 "window (with --service; default: unlimited)"),
        Opt("autopilot", default=False,
            help="Run the verify-or-revert control loop "
                 "(jepsen_tpu/autopilot.py) over the service: "
                 "doctor/SLO findings execute their remedies, every "
                 "action banked and verified (with --service)"),
        Opt("clear_quarantine", default=False,
            help="Discard the autopilot quarantine persisted in "
                 "this store's ledger instead of rehydrating it "
                 "(with --service --autopilot; the clear itself is "
                 "banked)"),
        Opt("replica_id", metavar="ID",
            help="Fleet replica identity banked on heartbeats "
                 "(with --service; default: env "
                 "JEPSEN_TPU_REPLICA_ID, else host-pid)"),
    ]

    def run(parsed: Parsed):
        from . import web
        o = parsed.options
        svc = None
        if o.get("service"):
            from .service import Service
            if o.get("clear_quarantine"):
                # env, not a kwarg: the Supervisor is constructed
                # inside Service.start() — the escape hatch must be
                # visible wherever rehydration happens
                os.environ["JEPSEN_TPU_AUTOPILOT_CLEAR_QUARANTINE"] \
                    = "1"
            svc = Service(o["store_root"],
                          workers=o.get("workers") or 1,
                          quota_device_s=o.get("quota_device_s"),
                          autopilot=bool(o.get("autopilot")),
                          replica_id=o.get("replica_id"))
        server = web.serve(host=o["host"], port=o["port"],
                           store_root=o["store_root"], service=svc)
        if svc is not None:
            # re-warm cached bucket plans only AFTER the bind
            # succeeded — minutes of XLA compiles must not precede
            # an EADDRINUSE
            warmed = svc.rewarm()
            if warmed:
                print(f"Re-warmed {len(warmed)} cached bucket "
                      "plan(s) from fs_cache")
        base = f"http://{o['host']}:{server.server_port}"
        print(f"Listening on {base}/")
        print(f"Live run status: {base}/status "
              f"(JSON: {base}/status.json)")
        print(f"Device observatory: {base}/devices "
              f"· occupancy: {base}/occupancy "
              f"· doctor: {base}/doctor "
              f"· slo: {base}/slo "
              f"· autopilot: {base}/autopilot "
              f"· fleet: {base}/fleet")
        if svc is not None:
            print(f"Checker service: POST {base}/check "
                  f"· events: {base}/events "
                  f"({svc.workers} worker(s))"
                  + (" · autopilot ON"
                     if svc.autopilot_enabled else ""))
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            if svc is not None:
                svc.close()
        return EXIT_OK

    return {"serve": {"opt_spec": spec, "run": run}}


def main(subcommands: dict, argv: Optional[Sequence[str]] = None) -> None:
    """sys.exit with run_cli's code; the -main analog (cli.clj:521)."""
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s [%(name)s] %(message)s")
    sys.exit(run_cli(subcommands, sys.argv[1:] if argv is None else argv))
