// faultfs — a FUSE passthrough filesystem with programmable fault
// injection. The TPU-native build's CharybdeFS equivalent: the
// reference clones and compiles scylladb/charybdefs (C++/Thrift) on
// each node (charybdefs/src/jepsen/charybdefs.clj:40-66) and drives it
// through a Thrift RPC "cookbook" (:68-86). This is a from-scratch
// redesign: same capability (passthrough FS where any operation can be
// made to fail with EIO or stall, globally, probabilistically, or by
// path substring) with a much smaller control surface — a magic
// control file inside the mount (".faultfs_ctl") accepts one-line
// commands, so the nemesis drives it with plain `echo >` over the
// control layer instead of a Thrift stack.
//
//   mount:    faultfs <backing-dir> <mountpoint> [fuse options]
//   control:  echo "eio all"            > /faulty/.faultfs_ctl
//             echo "eio p 0.01"         > /faulty/.faultfs_ctl
//             echo "eio path state.log" > /faulty/.faultfs_ctl
//             echo "delay ms 100 p 0.5" > /faulty/.faultfs_ctl
//             echo "clear"              > /faulty/.faultfs_ctl
//   inspect:  cat /faulty/.faultfs_ctl
//
// Build (on the db node, like the clock programs and the reference's
// on-node charybdefs build): g++ -O2 -o faultfs faultfs.cc \
//     $(pkg-config fuse3 --cflags --libs)
// Needs libfuse3-dev; the nemesis wrapper installs it.

#define FUSE_USE_VERSION 31

#include <fuse3/fuse.h>

#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <mutex>
#include <random>
#include <string>

#include <dirent.h>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/statvfs.h>
#include <sys/time.h>
#include <unistd.h>

namespace {

constexpr const char *kCtlName = "/.faultfs_ctl";

struct FaultState {
  bool eio_all = false;
  double eio_p = 0.0;          // probabilistic EIO
  std::string eio_path;        // substring match -> EIO
  double delay_p = 0.0;        // probabilistic delay
  long delay_ms = 0;
  std::mutex mu;
  std::mt19937_64 rng{0xFA17FA17};

  std::string describe() {
    std::lock_guard<std::mutex> lk(mu);  // races apply_command
    char buf[256];
    std::snprintf(buf, sizeof buf,
                  "eio_all=%d eio_p=%.4f eio_path=%s delay_ms=%ld "
                  "delay_p=%.4f\n",
                  eio_all ? 1 : 0, eio_p,
                  eio_path.empty() ? "-" : eio_path.c_str(), delay_ms,
                  delay_p);
    return buf;
  }
};

FaultState g_state;
std::string g_backing;

// -1 = inject EIO; otherwise apply any configured delay and continue.
int check_fault(const char *path) {
  std::lock_guard<std::mutex> lk(g_state.mu);
  if (g_state.delay_ms > 0) {
    double roll =
        std::uniform_real_distribution<>(0, 1)(g_state.rng);
    if (g_state.delay_p >= 1.0 || roll < g_state.delay_p) {
      struct timespec ts = {g_state.delay_ms / 1000,
                            (g_state.delay_ms % 1000) * 1000000L};
      nanosleep(&ts, nullptr);
    }
  }
  if (g_state.eio_all) return -1;
  if (!g_state.eio_path.empty() && path != nullptr &&
      std::strstr(path, g_state.eio_path.c_str()) != nullptr)
    return -1;
  if (g_state.eio_p > 0.0) {
    double roll =
        std::uniform_real_distribution<>(0, 1)(g_state.rng);
    if (roll < g_state.eio_p) return -1;
  }
  return 0;
}

void apply_command(const std::string &cmd) {
  std::lock_guard<std::mutex> lk(g_state.mu);
  char a[64] = {0}, b[64] = {0};
  double x = 0;
  if (cmd.rfind("clear", 0) == 0) {
    g_state.eio_all = false;
    g_state.eio_p = 0;
    g_state.eio_path.clear();
    g_state.delay_p = 0;
    g_state.delay_ms = 0;
  } else if (cmd == "eio all") {
    g_state.eio_all = true;
  } else if (std::sscanf(cmd.c_str(), "eio p %lf", &x) == 1) {
    g_state.eio_p = x;
  } else if (std::sscanf(cmd.c_str(), "eio path %63s", a) == 1) {
    g_state.eio_path = a;
  } else if (std::sscanf(cmd.c_str(), "delay ms %63s p %lf", a, &x) ==
             2) {
    g_state.delay_ms = std::strtol(a, nullptr, 10);
    g_state.delay_p = x;
  } else if (std::sscanf(cmd.c_str(), "delay ms %63s", a) == 1) {
    g_state.delay_ms = std::strtol(a, nullptr, 10);
    g_state.delay_p = 1.0;
  } else {
    std::fprintf(stderr, "faultfs: unknown command: %s (b=%s)\n",
                 cmd.c_str(), b);
  }
}

std::string real_path(const char *path) { return g_backing + path; }

bool is_ctl(const char *path) {
  return std::strcmp(path, kCtlName) == 0;
}

#define FAULT_GUARD(path)            \
  do {                               \
    if (check_fault(path) != 0) return -EIO; \
  } while (0)

int ff_getattr(const char *path, struct stat *st,
               struct fuse_file_info *) {
  if (is_ctl(path)) {
    std::memset(st, 0, sizeof *st);
    st->st_mode = S_IFREG | 0666;
    st->st_nlink = 1;
    st->st_size = 4096;
    return 0;
  }
  FAULT_GUARD(path);
  return lstat(real_path(path).c_str(), st) == -1 ? -errno : 0;
}

int ff_readdir(const char *path, void *buf, fuse_fill_dir_t fill,
               off_t, struct fuse_file_info *,
               enum fuse_readdir_flags) {
  FAULT_GUARD(path);
  DIR *dp = opendir(real_path(path).c_str());
  if (dp == nullptr) return -errno;
  struct dirent *de;
  while ((de = readdir(dp)) != nullptr)
    fill(buf, de->d_name, nullptr, 0, (fuse_fill_dir_flags)0);
  closedir(dp);
  return 0;
}

int ff_open(const char *path, struct fuse_file_info *fi) {
  if (is_ctl(path)) return 0;
  FAULT_GUARD(path);
  int fd = open(real_path(path).c_str(), fi->flags);
  if (fd == -1) return -errno;
  fi->fh = fd;
  return 0;
}

int ff_create(const char *path, mode_t mode,
              struct fuse_file_info *fi) {
  if (is_ctl(path)) return 0;
  FAULT_GUARD(path);
  int fd = open(real_path(path).c_str(), fi->flags, mode);
  if (fd == -1) return -errno;
  fi->fh = fd;
  return 0;
}

int ff_read(const char *path, char *buf, size_t size, off_t off,
            struct fuse_file_info *fi) {
  if (is_ctl(path)) {
    std::string s = g_state.describe();
    if ((size_t)off >= s.size()) return 0;
    size_t n = std::min(size, s.size() - off);
    std::memcpy(buf, s.data() + off, n);
    return (int)n;
  }
  FAULT_GUARD(path);
  ssize_t n = pread((int)fi->fh, buf, size, off);
  return n == -1 ? -errno : (int)n;
}

int ff_write(const char *path, const char *buf, size_t size, off_t off,
             struct fuse_file_info *fi) {
  if (is_ctl(path)) {
    std::string cmd(buf, size);
    while (!cmd.empty() &&
           (cmd.back() == '\n' || cmd.back() == ' '))
      cmd.pop_back();
    apply_command(cmd);
    return (int)size;
  }
  FAULT_GUARD(path);
  ssize_t n = pwrite((int)fi->fh, buf, size, off);
  return n == -1 ? -errno : (int)n;
}

int ff_release(const char *path, struct fuse_file_info *fi) {
  if (!is_ctl(path)) close((int)fi->fh);
  return 0;
}

int ff_fsync(const char *path, int datasync,
             struct fuse_file_info *fi) {
  if (is_ctl(path)) return 0;
  FAULT_GUARD(path);
  int r = datasync ? fdatasync((int)fi->fh) : fsync((int)fi->fh);
  return r == -1 ? -errno : 0;
}

int ff_truncate(const char *path, off_t size,
                struct fuse_file_info *) {
  if (is_ctl(path)) return 0;
  FAULT_GUARD(path);
  return truncate(real_path(path).c_str(), size) == -1 ? -errno : 0;
}

int ff_unlink(const char *path) {
  FAULT_GUARD(path);
  return unlink(real_path(path).c_str()) == -1 ? -errno : 0;
}

int ff_mkdir(const char *path, mode_t mode) {
  FAULT_GUARD(path);
  return mkdir(real_path(path).c_str(), mode) == -1 ? -errno : 0;
}

int ff_rmdir(const char *path) {
  FAULT_GUARD(path);
  return rmdir(real_path(path).c_str()) == -1 ? -errno : 0;
}

int ff_rename(const char *from, const char *to, unsigned int) {
  FAULT_GUARD(from);
  return rename(real_path(from).c_str(), real_path(to).c_str()) == -1
             ? -errno
             : 0;
}

int ff_statfs(const char *path, struct statvfs *st) {
  return statvfs(real_path(path).c_str(), st) == -1 ? -errno : 0;
}

int ff_utimens(const char *path, const struct timespec tv[2],
               struct fuse_file_info *) {
  if (is_ctl(path)) return 0;
  FAULT_GUARD(path);
  return utimensat(AT_FDCWD, real_path(path).c_str(), tv,
                   AT_SYMLINK_NOFOLLOW) == -1
             ? -errno
             : 0;
}

int ff_chmod(const char *path, mode_t mode, struct fuse_file_info *) {
  FAULT_GUARD(path);
  return chmod(real_path(path).c_str(), mode) == -1 ? -errno : 0;
}

}  // namespace

int main(int argc, char *argv[]) {
  if (argc < 3) {
    std::fprintf(stderr,
                 "usage: faultfs <backing-dir> <mountpoint> "
                 "[fuse options]\n");
    return 2;
  }
  g_backing = argv[1];
  // strip the backing dir from the argv FUSE parses
  static struct fuse_operations ops = {};
  ops.getattr = ff_getattr;
  ops.readdir = ff_readdir;
  ops.open = ff_open;
  ops.create = ff_create;
  ops.read = ff_read;
  ops.write = ff_write;
  ops.release = ff_release;
  ops.fsync = ff_fsync;
  ops.truncate = ff_truncate;
  ops.unlink = ff_unlink;
  ops.mkdir = ff_mkdir;
  ops.rmdir = ff_rmdir;
  ops.rename = ff_rename;
  ops.statfs = ff_statfs;
  ops.utimens = ff_utimens;
  ops.chmod = ff_chmod;
  int fargc = argc - 1;
  char **fargv = argv + 1;
  fargv[0] = argv[0];
  return fuse_main(fargc, fargv, &ops, nullptr);
}
