// faultlib — LD_PRELOAD I/O fault injector.
//
// The process-scoped sibling of faultfs: where faultfs interposes at
// the filesystem boundary (FUSE, needs root + /dev/fuse), faultlib
// interposes at the libc boundary, the same mechanism the reference
// uses for clock virtualization (libfaketime, faketime.clj:8-22).
// Wrap a DB process with LD_PRELOAD=faultlib.so and acknowledged
// writes/fsyncs start failing with EIO — no kernel support, no
// privileges, works in any container. This is the path the CI
// integration tests exercise against a live toykv cluster.
//
// Config via environment:
//   FAULTLIB_PATH      substring of paths to target (default: all)
//   FAULTLIB_EIO_P     probability [0,1] a matching write/fsync
//                      returns EIO (default 0)
//   FAULTLIB_EIO_AFTER fail every matching call after this many
//                      successes (default -1 = never)
//   FAULTLIB_DELAY_MS  sleep this long before each matching call
//   FAULTLIB_CONF      path to a file re-read before each decision:
//                      lines "eio_p=0.5", "eio_after=100", "path=x",
//                      "delay_ms=10", empty/missing file = clear —
//                      lets a nemesis retarget a live process
//
// Build: g++ -O2 -shared -fPIC -o faultlib.so faultlib.cc -ldl
//
// Intercepts: write, pwrite, fsync, fdatasync (the acknowledged-
// durability surface; reads stay untouched so the victim can limp on).

#define _GNU_SOURCE 1

#include <atomic>
#include <cstdarg>
#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <mutex>
#include <random>
#include <string>
#include <unordered_map>

#include <dlfcn.h>
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

using write_fn = ssize_t (*)(int, const void *, size_t);
using pwrite_fn = ssize_t (*)(int, const void *, size_t, off_t);
using fsync_fn = int (*)(int);
using open_fn = int (*)(const char *, int, ...);
using close_fn = int (*)(int);

write_fn real_write;
pwrite_fn real_pwrite;
fsync_fn real_fsync;
fsync_fn real_fdatasync;
open_fn real_open;
close_fn real_close;

struct Config {
  std::string path;
  double eio_p = 0.0;
  long eio_after = -1;
  long delay_ms = 0;
};

std::mutex g_mu;
Config g_cfg;
std::string g_conf_file;
time_t g_conf_mtime = 0;
std::atomic<long> g_matched{0};
std::unordered_map<int, std::string> g_fd_paths;

void load_env() {
  const char *p = getenv("FAULTLIB_PATH");
  if (p) g_cfg.path = p;
  const char *e = getenv("FAULTLIB_EIO_P");
  if (e) g_cfg.eio_p = atof(e);
  const char *a = getenv("FAULTLIB_EIO_AFTER");
  if (a) g_cfg.eio_after = atol(a);
  const char *d = getenv("FAULTLIB_DELAY_MS");
  if (d) g_cfg.delay_ms = atol(d);
  const char *c = getenv("FAULTLIB_CONF");
  if (c) g_conf_file = c;
}

void reload_conf_locked() {
  if (g_conf_file.empty()) return;
  struct stat st;
  if (stat(g_conf_file.c_str(), &st) != 0) {
    // missing file = cleared faults; reset the mtime cache so a conf
    // recreated within the same second still loads
    g_cfg.eio_p = 0;
    g_cfg.eio_after = -1;
    g_cfg.delay_ms = 0;
    g_conf_mtime = 0;
    return;
  }
  if (st.st_mtime == g_conf_mtime) return;
  g_conf_mtime = st.st_mtime;
  FILE *fh = fopen(g_conf_file.c_str(), "r");
  if (!fh) return;
  Config fresh;
  fresh.path = g_cfg.path;
  char line[256];
  while (fgets(line, sizeof line, fh)) {
    double x;
    char s[200];
    if (sscanf(line, "eio_p=%lf", &x) == 1) fresh.eio_p = x;
    else if (sscanf(line, "eio_after=%lf", &x) == 1)
      fresh.eio_after = (long)x;
    else if (sscanf(line, "delay_ms=%lf", &x) == 1)
      fresh.delay_ms = (long)x;
    else if (sscanf(line, "path=%199s", s) == 1) fresh.path = s;
  }
  fclose(fh);
  g_cfg = fresh;
  g_matched = 0;  // eio_after counts from each retarget
}

// Lazy init from the first interposed call: an
// __attribute__((constructor)) would run before this TU's C++ global
// initializers, which then default-construct g_cfg over the loaded
// values. A function-local static initializes exactly once, after
// globals, thread-safely.
void ensure_init() {
  static bool once = [] {
    real_write = (write_fn)dlsym(RTLD_NEXT, "write");
    real_pwrite = (pwrite_fn)dlsym(RTLD_NEXT, "pwrite");
    real_fsync = (fsync_fn)dlsym(RTLD_NEXT, "fsync");
    real_fdatasync = (fsync_fn)dlsym(RTLD_NEXT, "fdatasync");
    real_open = (open_fn)dlsym(RTLD_NEXT, "open");
    real_close = (close_fn)dlsym(RTLD_NEXT, "close");
    load_env();
    return true;
  }();
  (void)once;
}

bool fd_matches(int fd) {
  if (g_cfg.path.empty()) return true;
  auto it = g_fd_paths.find(fd);
  if (it != g_fd_paths.end())
    return it->second.find(g_cfg.path) != std::string::npos;
  // fall back to /proc resolution (fd opened before interposition)
  char link[64], target[512];
  snprintf(link, sizeof link, "/proc/self/fd/%d", fd);
  ssize_t n = readlink(link, target, sizeof target - 1);
  if (n <= 0) return false;
  target[n] = 0;
  return strstr(target, g_cfg.path.c_str()) != nullptr;
}

// true -> caller should fail with EIO. The sleep and the probability
// roll happen on a copy OUTSIDE the lock, so a latency fault on one
// fd never stalls the whole process's interposed I/O.
bool inject(int fd) {
  ensure_init();
  Config cfg;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    reload_conf_locked();
    if (g_cfg.eio_p <= 0 && g_cfg.eio_after < 0 &&
        g_cfg.delay_ms <= 0)
      return false;
    if (!fd_matches(fd)) return false;
    cfg = g_cfg;
  }
  if (cfg.delay_ms > 0) {
    struct timespec ts = {cfg.delay_ms / 1000,
                          (cfg.delay_ms % 1000) * 1000000L};
    nanosleep(&ts, nullptr);
  }
  long seen = g_matched.fetch_add(1);
  if (cfg.eio_after >= 0 && seen >= cfg.eio_after) return true;
  if (cfg.eio_p > 0) {
    static thread_local std::mt19937_64 rng{
        0xFA17F11Eull ^ (unsigned long)gettid()};
    double roll = std::uniform_real_distribution<>(0, 1)(rng);
    if (roll < cfg.eio_p) return true;
  }
  return false;
}

}  // namespace

extern "C" {

int open(const char *path, int flags, ...) {
  ensure_init();
  mode_t mode = 0;
  if (flags & O_CREAT) {
    va_list ap;
    va_start(ap, flags);
    mode = va_arg(ap, mode_t);
    va_end(ap);
  }
  int fd = real_open(path, flags, mode);
  if (fd >= 0) {
    std::lock_guard<std::mutex> lk(g_mu);
    g_fd_paths[fd] = path;
  }
  return fd;
}

int close(int fd) {
  ensure_init();
  {
    // recycled fd numbers must not inherit a stale path match
    std::lock_guard<std::mutex> lk(g_mu);
    g_fd_paths.erase(fd);
  }
  return real_close(fd);
}

ssize_t write(int fd, const void *buf, size_t count) {
  if (inject(fd)) {
    errno = EIO;
    return -1;
  }
  return real_write(fd, buf, count);
}

ssize_t pwrite(int fd, const void *buf, size_t count, off_t off) {
  if (inject(fd)) {
    errno = EIO;
    return -1;
  }
  return real_pwrite(fd, buf, count, off);
}

int fsync(int fd) {
  if (inject(fd)) {
    errno = EIO;
    return -1;
  }
  return real_fsync(fd);
}

int fdatasync(int fd) {
  if (inject(fd)) {
    errno = EIO;
    return -1;
  }
  return real_fdatasync(fd);
}

}  // extern "C"
