// Strobe the system wall clock back and forth.
//
// TPU-framework C++ port of the reference's clock-strobe tool
// (jepsen/resources/strobe-time.c, driven from jepsen/src/jepsen/nemesis/
// time.clj:92-96): flips the clock by +/- delta every period, for
// duration seconds — a brutal fault for leases and timeouts.
//
// usage: strobe-time <delta-ms> <period-ms> <duration-s>

#include <cstdio>
#include <cstdlib>
#include <ctime>

namespace {

// Add `delta_ns` to the realtime clock.
int shift_clock(int64_t delta_ns) {
  timespec ts;
  if (clock_gettime(CLOCK_REALTIME, &ts) != 0) {
    std::perror("clock_gettime");
    return 1;
  }
  int64_t ns = ts.tv_nsec + delta_ns % 1000000000;
  int64_t s = ts.tv_sec + delta_ns / 1000000000;
  if (ns >= 1000000000) {
    ns -= 1000000000;
    s += 1;
  } else if (ns < 0) {
    ns += 1000000000;
    s -= 1;
  }
  ts.tv_sec = static_cast<time_t>(s);
  ts.tv_nsec = static_cast<long>(ns);
  if (clock_settime(CLOCK_REALTIME, &ts) != 0) {
    std::perror("clock_settime");
    return 1;
  }
  return 0;
}

}  // namespace

int main(int argc, char **argv) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: %s <delta-ms> <period-ms> <duration-s>\n"
                 "Strobes the clock +/- delta every period, for duration.\n",
                 argv[0]);
    return 1;
  }

  const int64_t delta_ns =
      static_cast<int64_t>(std::atof(argv[1]) * 1e6);
  const int64_t period_ns =
      static_cast<int64_t>(std::atof(argv[2]) * 1e6);
  const double duration_s = std::atof(argv[3]);

  // Track elapsed time with the monotonic clock: the realtime clock is
  // the thing we're mangling.
  timespec start;
  clock_gettime(CLOCK_MONOTONIC, &start);

  const timespec nap = {static_cast<time_t>(period_ns / 1000000000),
                        static_cast<long>(period_ns % 1000000000)};
  bool up = true;
  while (true) {
    timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    const double elapsed = (now.tv_sec - start.tv_sec) +
                           (now.tv_nsec - start.tv_nsec) / 1e9;
    if (elapsed >= duration_s) break;
    if (shift_clock(up ? delta_ns : -delta_ns) != 0) return 1;
    up = !up;
    nanosleep(&nap, nullptr);
  }

  // Leave the clock where it started: an even number of flips cancels;
  // if we ended mid-flip, undo the last shift.
  if (!up) shift_clock(-delta_ns);
  return 0;
}
