// Shift the system wall clock by a delta, in milliseconds.
//
// TPU-framework C++ port of the reference's clock-bump tool
// (jepsen/resources/bump-time.c, driven from jepsen/src/jepsen/nemesis/
// time.clj:86-90): used by the clock nemesis to introduce clock skew on
// DB nodes. Prints the new wall-clock time in fractional POSIX seconds.
//
// usage: bump-time <delta-ms>   (requires CAP_SYS_TIME / root)

#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <ctime>

int main(int argc, char **argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <delta>, where delta is in ms\n", argv[0]);
    return 1;
  }

  const double delta_ms = std::atof(argv[1]);
  const int64_t delta_ns = static_cast<int64_t>(delta_ms * 1e6);

  timespec ts;
  if (clock_gettime(CLOCK_REALTIME, &ts) != 0) {
    std::perror("clock_gettime");
    return 1;
  }

  int64_t ns = ts.tv_nsec + delta_ns % 1000000000;
  int64_t s = ts.tv_sec + delta_ns / 1000000000;
  // Renormalize so tv_nsec lands in [0, 1e9).
  if (ns >= 1000000000) {
    ns -= 1000000000;
    s += 1;
  } else if (ns < 0) {
    ns += 1000000000;
    s -= 1;
  }
  ts.tv_sec = static_cast<time_t>(s);
  ts.tv_nsec = static_cast<long>(ns);

  if (clock_settime(CLOCK_REALTIME, &ts) != 0) {
    std::perror("clock_settime");
    return 1;
  }

  std::printf("%" PRId64 ".%09ld\n", static_cast<int64_t>(ts.tv_sec),
              ts.tv_nsec);
  return 0;
}
